"""Continuous-batching decode scheduler (models/scheduler.py).

The load-bearing contract: a request generates EXACTLY the tokens it
would generate alone on the sequential path, no matter what else shares
the slot pool — greedy, seeded sampling, mixed lengths, EOS mid-flight
while new rows are admitted into freed slots.
"""
import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import pytest
from werkzeug.test import Client

from kubeflow_tpu.models.generate import generate
from kubeflow_tpu.models.llama import CONFIGS, Llama
from kubeflow_tpu.models.paged import PagedDecodeScheduler
from kubeflow_tpu.models.scheduler import DecodeScheduler
from kubeflow_tpu.models.serve import GenerationService, create_app


@pytest.fixture(scope="module")
def model_and_params():
    cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))[
        "params"]
    return model, params


def sequential(model, params, rows, **kw):
    """The per-request reference: one generate() call, exactly what the
    lock-serialized path runs."""
    longest = max(len(r) for r in rows)
    prompt = jnp.array([r + [0] * (longest - len(r)) for r in rows],
                       jnp.int32)
    mask = jnp.array([[1] * len(r) + [0] * (longest - len(r))
                      for r in rows], bool)
    seed = kw.pop("seed", 0)
    out = generate(model, params, prompt, prompt_mask=mask,
                   rng=jax.random.key(seed), **kw)
    return jax.device_get(out).tolist()


def test_single_row_greedy_token_equal(model_and_params):
    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=4, slot_len=64, quantum=4)
    rows = [[5, 9, 2, 7]]
    got = sched.submit(rows, max_new_tokens=6).result()
    assert got == sequential(model, params, rows, max_new_tokens=6)


def test_single_row_seeded_topk_token_equal(model_and_params):
    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=4, slot_len=64, quantum=4)
    rows = [[3, 1, 4, 1, 5]]
    got = sched.submit(rows, max_new_tokens=7, temperature=0.8, top_k=8,
                       seed=11).result()
    assert got == sequential(model, params, rows, max_new_tokens=7,
                             temperature=0.8, top_k=8, seed=11)


def test_multi_row_mixed_length_request(model_and_params):
    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=4, slot_len=64, quantum=4)
    rows = [[5, 9], [7, 1, 4, 8], [2]]
    got = sched.submit(rows, max_new_tokens=5).result()
    assert got == sequential(model, params, rows, max_new_tokens=5)


def test_budget_one_and_immediate_eos(model_and_params):
    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=2, slot_len=64, quantum=4)
    rows = [[5, 9, 2, 7]]
    # n == 1: completes at admission, never takes a slot.
    assert sched.submit(rows, max_new_tokens=1).result() == sequential(
        model, params, rows, max_new_tokens=1)
    # EOS on the FIRST sampled token: the row must right-pad with EOS
    # without ever decoding.
    first = sequential(model, params, rows, max_new_tokens=1)[0][0]
    got = sched.submit(rows, max_new_tokens=5, eos_token=first).result()
    assert got == sequential(model, params, rows, max_new_tokens=5,
                             eos_token=first)
    assert got[0][1:] == [first] * 4


def test_midflight_eos_evicts_and_refills(model_and_params):
    """Rows that EOS mid-flight free their slots for queued rows while
    other rows keep decoding — and every output stays token-equal.  With
    2 slots and 6 concurrent requests the queue MUST refill mid-flight."""
    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=2, slot_len=64, quantum=2)
    ref = sequential(model, params, [[5, 9, 2, 7]], max_new_tokens=10)
    eos = ref[0][4]  # EOSes at decode step 4 of 10
    reqs = [
        ([[5, 9, 2, 7]], dict(max_new_tokens=10, eos_token=eos)),
        ([[1, 2, 3]], dict(max_new_tokens=12)),
        ([[4, 4]], dict(max_new_tokens=6, temperature=0.5, top_k=4,
                        seed=3)),
        ([[8, 8, 8, 8, 8]], dict(max_new_tokens=9)),
        ([[9, 7, 5]], dict(max_new_tokens=4, eos_token=eos)),
        ([[2, 2, 2]], dict(max_new_tokens=8)),
    ]
    outs = {}

    def client(i, rows, kw):
        outs[i] = sched.submit(rows, **kw).result()

    threads = [threading.Thread(target=client, args=(i, r, kw))
               for i, (r, kw) in enumerate(reqs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, (rows, kw) in enumerate(reqs):
        assert outs[i] == sequential(model, params, rows, **kw), i
    stats = sched.stats()
    assert stats["admitted_total"] == stats["evicted_total"] == 6
    assert stats["active_rows"] == 0 and stats["queued_rows"] == 0


def test_request_wider_than_pool_pends_rows(model_and_params):
    """A request with more rows than the pool has slots decodes in
    waves through the pending-insert list — outputs still equal."""
    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=2, slot_len=64, quantum=3)
    rows = [[5, 9], [7, 1], [2, 4], [8, 3], [6, 6]]
    got = sched.submit(rows, max_new_tokens=5).result()
    assert got == sequential(model, params, rows, max_new_tokens=5)
    assert sched.stats()["evicted_total"] == 5


def test_slot_len_bound_raises(model_and_params):
    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=2, slot_len=16, quantum=2)
    with pytest.raises(ValueError, match="slot length"):
        sched.submit([[1] * 10], max_new_tokens=10)


def test_scheduler_crash_fails_requests_then_service_falls_back(
        model_and_params, monkeypatch):
    """A loop crash must fail in-flight requests with the error (never
    hang them) and mark the scheduler dead; the SERVICE then falls back
    to the lock-serialized path for subsequent requests."""
    model, params = model_and_params
    service = GenerationService(model, params)
    create_app(service, model_name="llama_debug")  # attaches telemetry
    sched = service._scheduler_or_none()
    assert sched is not None

    def boom(*a, **k):
        raise RuntimeError("injected scheduler fault")

    monkeypatch.setattr(sched, "_run_quantum", boom)
    with pytest.raises(RuntimeError, match="injected scheduler fault"):
        service.generate([[5, 9, 2]], max_new_tokens=4)
    assert not sched.alive
    # Next request: lock path, still serves.
    out = service.generate([[5, 9, 2]], max_new_tokens=4)
    assert out == sequential(model, params, [[5, 9, 2]], max_new_tokens=4)


def test_serve_queue_depth_counts_pending_rows(model_and_params):
    """ISSUE 8 satellite: serve_queue_depth gauges pending scheduler
    queue ROWS (not lock waiters).  Submissions stack the gauge while
    the loop is held; it drains to zero once decoding runs."""
    model, params = model_and_params
    service = GenerationService(model, params)
    client = Client(create_app(service, model_name="llama_debug"))
    sched = service._scheduler_or_none()
    orig_start = sched.start
    sched.start = lambda: None  # hold the loop: submissions only queue
    try:
        results = {}
        threads = [threading.Thread(
            target=lambda i=i: results.update(
                {i: service.generate([[5 + i, 9, 2]], max_new_tokens=4)}))
            for i in range(3)]
        for t in threads:
            t.start()
        deadline = time.time() + 10
        while time.time() < deadline:
            text = client.get("/metrics").get_data(as_text=True)
            if "serve_queue_depth 3.0" in text:
                break
            time.sleep(0.02)
        else:
            pytest.fail(f"queue depth never reached 3: {text}")
    finally:
        sched.start = orig_start
        sched.start()
    for t in threads:
        t.join()
    text = client.get("/metrics").get_data(as_text=True)
    assert "serve_queue_depth 0.0" in text
    assert "serve_scheduler_admitted_rows_total 3.0" in text
    assert "serve_scheduler_evicted_rows_total 3.0" in text
    assert "serve_decode_slots_active 0.0" in text
    for i in range(3):
        assert results[i] == sequential(
            model, params, [[5 + i, 9, 2]], max_new_tokens=4)


def test_http_outputs_identical_scheduler_on_vs_off(model_and_params,
                                                    monkeypatch):
    """KFT_SERVE_SCHEDULER=0 pins the lock path; both engines must
    serve identical HTTP responses (greedy AND seeded sampling)."""
    model, params = model_and_params
    body = {"tokens": [[5, 9, 2], [7, 7]], "max_new_tokens": 5,
            "temperature": 0.7, "top_k": 5, "seed": 9}
    # The env gate is read per request: the "on" arm must run BEFORE
    # the env flips, and prove it really used the scheduler.
    on_service = GenerationService(model, params)
    on = Client(create_app(on_service, model_name="m"))
    r_on = on.post("/v1/generate", json=body)
    assert on_service._scheduler is not None
    assert on_service._scheduler.stats()["evicted_total"] >= 2
    monkeypatch.setenv("KFT_SERVE_SCHEDULER", "0")
    off_service = GenerationService(model, params)
    off = Client(create_app(off_service, model_name="m"))
    r_off = off.post("/v1/generate", json=body)
    assert r_on.status_code == r_off.status_code == 200
    assert r_on.get_json()["tokens"] == r_off.get_json()["tokens"]
    assert off_service._scheduler is None  # really took the lock path


def test_seq2seq_stays_on_lock_path(monkeypatch):
    """ISSUE 8 satellite: the encoder pass is not a prefill — the
    seq2seq service must never grow a scheduler, even with the gate
    forced on, and its trace keeps the single generate span."""
    from kubeflow_tpu.models.serve import load_service

    monkeypatch.setenv("KFT_SERVE_SCHEDULER", "1")
    svc = load_service("t5_debug")
    client = Client(create_app(svc, model_name="t5_debug"))
    resp = client.post("/v1/generate", json={
        "tokens": [[5, 9, 2]], "max_new_tokens": 4,
    })
    assert resp.status_code == 200
    assert not hasattr(svc, "_scheduler") or svc._scheduler is None
    traces = client.get("/debug/traces").get_json()["traces"]
    assert [s["name"] for s in traces[-1]["spans"]] == [
        "admit", "queue", "generate"]


def test_sharded_serve_scheduler_token_equal(devices8):
    """ISSUE 8 acceptance, extended by ISSUE 20: the service drives a
    GSPMD-sharded model on 8 forced host devices — params via
    shard_params, and (now that the mesh routes to the paged engine) the
    page pool split over the data axes — token-equal to the unsharded
    path."""
    from kubeflow_tpu.models.paged import PagedDecodeScheduler
    from kubeflow_tpu.models.serve import load_service

    plain = load_service("llama_debug", max_seq_len=64)
    spmd = load_service("llama_debug", max_seq_len=64,
                        mesh_spec="tp=2,fsdp=4")
    create_app(plain, model_name="m")
    create_app(spmd, model_name="m")
    assert spmd.mesh is not None
    rows = [[5, 9, 2, 7], [3, 3]]
    a = plain.generate(rows, max_new_tokens=6)
    b = spmd.generate(rows, max_new_tokens=6)
    assert a == b
    # Both requests really ran through schedulers; the sharded service
    # routes to the paged engine (no fallback recorded) and its pool —
    # rank-3 [pool_positions, kv_heads, head_dim] leaves — is split on
    # the pool axis across the fsdp=4 data devices.
    sched = spmd._scheduler
    assert isinstance(sched, PagedDecodeScheduler)
    assert spmd.scheduler_fallback is None
    assert sched.stats()["evicted_total"] >= 2
    assert sched.stats()["pool_shards"] == 4
    leaf = jax.tree.leaves(spmd.params)[0]
    assert len(leaf.sharding.device_set) > 1
    cache_leaf = next(x for x in jax.tree.leaves(sched._cache)
                      if getattr(x, "ndim", 0) >= 3)
    assert len(cache_leaf.sharding.device_set) > 1


# -- paged KV engine (models/paged.py, ISSUE 17) --------------------------
#
# The token-equality matrix: paged == contiguous == sequential, across
# greedy, seeded sampling, shared prefixes (copy-on-write divergence),
# chunked prefill interleaved with decode, and speculative decoding at
# its accept/reject boundaries.  The paged pool is an OPTIMIZATION —
# every test here pins that it is never a behavior change.


def _paged(model, params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("slot_len", 64)
    kw.setdefault("quantum", 4)
    kw.setdefault("page_len", 16)
    kw.setdefault("prefill_chunk", 16)
    return PagedDecodeScheduler(model, params, **kw)


def _pages_balanced(stats):
    """The drained-pool balance invariant: nothing active, and every
    non-null page is either free or resident in the prefix cache."""
    assert stats["pages_active"] == 0, stats
    assert (stats["pages_free"] + stats["pages_shared"]
            == stats["pages_total"] - 1), stats


def test_paged_greedy_matrix_token_equal(model_and_params):
    """paged == contiguous == sequential for a mixed-length greedy
    request whose rows span page boundaries (9 tokens over 16-token
    pages, 12 new tokens => 2 pages per row)."""
    model, params = model_and_params
    rows = [[5, 6, 7, 8, 9], [1, 2, 3], [4, 4, 4, 4, 4, 4, 4, 4, 4]]
    ref = sequential(model, params, rows, max_new_tokens=12)
    fixed = DecodeScheduler(model, params, slots=4, slot_len=64, quantum=4)
    assert fixed.submit(rows, max_new_tokens=12).result() == ref
    paged = _paged(model, params)
    assert paged.submit(rows, max_new_tokens=12).result() == ref
    _pages_balanced(paged.stats())


def test_paged_seeded_topk_token_equal(model_and_params):
    model, params = model_and_params
    rows = [[5, 6, 7, 8, 9], [1, 2, 3], [4, 4, 4, 4, 4, 4, 4, 4, 4]]
    kw = dict(max_new_tokens=12, temperature=0.8, top_k=5, seed=7)
    ref = sequential(model, params, rows, **kw)
    fixed = DecodeScheduler(model, params, slots=4, slot_len=64, quantum=4)
    assert fixed.submit(rows, **kw).result() == ref
    paged = _paged(model, params)
    assert paged.submit(rows, **kw).result() == ref


def test_paged_shared_prefix_cow_divergence(model_and_params):
    """Rows sharing a prompt prefix map to the SAME physical pages and
    still diverge correctly after it (copy-on-write by construction:
    decode writes land in row-owned pages, never shared ones).  Sharing
    is cross-request: the first request populates the cache (misses
    only), a follow-up with the same prefix hits it."""
    model, params = model_and_params
    sys_prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1] * 4  # 36 tokens = 2+ pages
    chats = [sys_prompt + [10 + i] for i in range(3)]
    paged = _paged(model, params)
    ref = sequential(model, params, chats, max_new_tokens=8)
    assert paged.submit(chats, max_new_tokens=8).result() == ref
    st = paged.stats()
    assert st["pages_shared"] > 0  # the prefix stayed resident
    assert st["prefix_hits"] == 0  # cold cache: sharing is cross-request
    assert st["prefix_misses"] > 0
    # Follow-up request with the same system prompt: cache hit, output
    # still exactly its own sequential continuation.
    tail = [sys_prompt + [50]]
    assert paged.submit(tail, max_new_tokens=8).result() == sequential(
        model, params, tail, max_new_tokens=8)
    st2 = paged.stats()
    assert st2["prefix_hits"] > 0
    _pages_balanced(st2)
    # Drained pool: no lane holds pages, the shared set persists.
    snap = paged.debug_pages()
    assert snap["lanes"] == {} and snap["shared"]


def test_paged_chunked_prefill_interleaves_with_eviction(model_and_params):
    """A long prompt prefills in page-sized chunks BETWEEN decode quanta
    while short requests EOS out and refill freed lanes mid-flight —
    every output token-equal, pool drains balanced.  2 lanes + 6
    threaded requests force both interleave and refill."""
    model, params = model_and_params
    paged = _paged(model, params, slots=2, quantum=2, page_len=8,
                   prefill_chunk=8)
    long_prompt = [(i * 7 + 3) % 250 + 1 for i in range(40)]  # 5 chunks
    ref = sequential(model, params, [[5, 9, 2, 7]], max_new_tokens=10)
    eos = ref[0][4]
    reqs = [
        ([long_prompt], dict(max_new_tokens=12)),
        ([[5, 9, 2, 7]], dict(max_new_tokens=10, eos_token=eos)),
        ([[1, 2, 3]], dict(max_new_tokens=12)),
        ([[4, 4]], dict(max_new_tokens=6, temperature=0.5, top_k=4,
                        seed=3)),
        ([[9, 7, 5]], dict(max_new_tokens=4, eos_token=eos)),
        ([long_prompt[:23]], dict(max_new_tokens=8)),
    ]
    outs = {}

    def client(i, rows, kw):
        outs[i] = paged.submit(rows, **kw).result()

    threads = [threading.Thread(target=client, args=(i, r, kw))
               for i, (r, kw) in enumerate(reqs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, (rows, kw) in enumerate(reqs):
        assert outs[i] == sequential(model, params, rows, **kw), i
    stats = paged.stats()
    assert stats["admitted_total"] == stats["evicted_total"] == 6
    _pages_balanced(stats)


def test_paged_spec_decode_zero_accept_boundary(model_and_params):
    """Speculative floor: a draft that NEVER agrees with the target
    (independent random init — deterministically disjoint argmaxes at
    this scale) forces the 0-accepted boundary every step.  Each verify
    still emits exactly one correct token: output token-equal, just no
    speedup."""
    model, params = model_and_params
    draft_params = model.init(jax.random.key(1),
                              jnp.ones((1, 8), jnp.int32))["params"]
    rows = [[5, 6, 7, 8, 9], [1, 2, 3], [4, 4, 4, 4, 4, 4, 4, 4, 4]]
    sp = _paged(model, params, draft_model=model,
                draft_params=draft_params, spec_tokens=3)
    assert sp.submit(rows, max_new_tokens=12).result() == sequential(
        model, params, rows, max_new_tokens=12)
    st = sp.stats()
    assert st["spec_proposed"] > 0
    assert st["spec_accepted"] == 0  # the boundary this test exists for


def test_paged_spec_decode_all_accept_boundary(model_and_params):
    """Speculative ceiling: draft == target accepts every proposal
    (greedy determinism), so each step emits k+1 tokens — and the output
    is still byte-identical to sequential."""
    model, params = model_and_params
    rows = [[5, 6, 7, 8, 9], [1, 2, 3], [4, 4, 4, 4, 4, 4, 4, 4, 4]]
    sp = _paged(model, params, draft_model=model, draft_params=params,
                spec_tokens=3)
    assert sp.submit(rows, max_new_tokens=12).result() == sequential(
        model, params, rows, max_new_tokens=12)
    st = sp.stats()
    assert st["spec_proposed"] > 0
    assert st["spec_accepted"] == st["spec_proposed"]


def test_paged_spec_decode_eos_inside_draft_window(model_and_params):
    """EOS landing MID-WINDOW (an accepted draft token is the EOS) must
    stop that row exactly there and right-pad — identical to the
    sequential EOS semantics, with tokens past the EOS discarded even
    though the verify step already scored them."""
    model, params = model_and_params
    rows = [[5, 9, 2, 7]]
    ref = sequential(model, params, rows, max_new_tokens=10)
    # Pick a token at positions 1..3 (inside the first k+1=4 window)
    # whose value has not already appeared — its first occurrence is the
    # stopping point on both engines.
    p = next(i for i in range(1, 4) if ref[0][i] not in ref[0][:i])
    eos = ref[0][p]
    sp = _paged(model, params, draft_model=model, draft_params=params,
                spec_tokens=3)
    got = sp.submit(rows, max_new_tokens=10, eos_token=eos).result()
    assert got == sequential(model, params, rows, max_new_tokens=10,
                             eos_token=eos)
    assert got[0][p + 1:] == [eos] * (9 - p)  # stopped at in-window EOS


def test_paged_env_gate_falls_back_to_fixed_pool(model_and_params,
                                                 monkeypatch):
    """KFT_SERVE_PAGED=0 restores the fixed-slot engine unchanged; the
    default service grows the paged one."""
    model, params = model_and_params
    on = GenerationService(model, params)
    create_app(on, model_name="m")
    assert isinstance(on._scheduler_or_none(), PagedDecodeScheduler)
    monkeypatch.setenv("KFT_SERVE_PAGED", "0")
    off = GenerationService(model, params)
    create_app(off, model_name="m")
    sched = off._scheduler_or_none()
    assert isinstance(sched, DecodeScheduler)
    assert not isinstance(sched, PagedDecodeScheduler)


def test_paged_page_len_must_divide_slot_len(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="divisor"):
        PagedDecodeScheduler(model, params, slots=2, slot_len=64,
                             page_len=24)


def test_paged_knob_validation_raises_and_reports(model_and_params,
                                                  monkeypatch):
    """Strict knobs (config.knob validate=): a bad KFT_SERVE_PAGE_LEN
    raises at the read site instead of silently serving the default,
    and /debug/knobs reports the rejection source."""
    from kubeflow_tpu.platform import config

    model, params = model_and_params
    monkeypatch.setenv("KFT_SERVE_PAGE_LEN", "banana")
    with pytest.raises(ValueError, match="not a valid int"):
        PagedDecodeScheduler(model, params, slots=2, slot_len=64)
    monkeypatch.setenv("KFT_SERVE_PAGE_LEN", "8192")
    with pytest.raises(ValueError, match="must be in"):
        PagedDecodeScheduler(model, params, slots=2, slot_len=64)
    monkeypatch.setenv("KFT_SERVE_SPEC_TOKENS", "-1")
    monkeypatch.delenv("KFT_SERVE_PAGE_LEN")
    with pytest.raises(ValueError, match="must be in"):
        PagedDecodeScheduler(model, params, slots=2, slot_len=64)
    monkeypatch.setenv("KFT_SERVE_PAGE_LEN", "8192")
    eff = config.effective()["KFT_SERVE_PAGE_LEN"]
    assert eff["source"] == "env-invalid" and eff["value"] == 64
    monkeypatch.setenv("KFT_SERVE_PAGE_LEN", "banana")
    eff = config.effective()["KFT_SERVE_PAGE_LEN"]
    assert eff["source"] == "env-unparseable" and eff["value"] == 64


def test_paged_submit_over_page_capacity_raises(model_and_params):
    """Worst-case page demand beyond the pool fails at submit (a clear
    error) instead of stalling admission forever."""
    model, params = model_and_params
    paged = PagedDecodeScheduler(model, params, slots=2, slot_len=64,
                                 quantum=2, page_len=16, num_pages=6)
    with pytest.raises(ValueError, match="KV pages"):
        paged.submit([[1, 2]] * 4, max_new_tokens=30)


def test_paged_rejects_spec_decode_under_mesh(model_and_params):
    """ISSUE 20 lifts the blanket mesh rejection — a mesh is now a
    first-class paged configuration — but speculative decoding under a
    mesh stays unsupported and must fail at construction, not on the
    first spec step."""
    from kubeflow_tpu.models.serve import load_service
    from kubeflow_tpu.train.run import parse_mesh

    model, params = model_and_params
    mesh = parse_mesh("tp=%d" % len(jax.devices()), len(jax.devices()))
    draft = load_service("llama_debug", max_seq_len=64)
    with pytest.raises(ValueError, match="[Ss]peculative"):
        PagedDecodeScheduler(model, params, mesh=mesh,
                             draft_model=draft.model,
                             draft_params=draft.params)
    # Mesh alone constructs fine (tp-only → a single replicated shard).
    sched = PagedDecodeScheduler(model, params, mesh=mesh, slots=2,
                                 slot_len=64, page_len=16)
    assert sched.pool_shards == 1
    sched.stop()


@pytest.mark.slow
def test_paged_soak_shared_prefix_invariants(model_and_params):
    """Paged-pool soak (serve-soak postsubmit): concurrent HTTP clients
    hammer chats sharing one system prompt.  Invariants: token equality
    per prompt (no cross-request page mixing), zero page aliasing
    outside the declared shared prefix at every live snapshot, prefix
    hits accrue, and the drained pool balances."""
    import json as _json
    import urllib.request

    model, params = model_and_params
    service = GenerationService(model, params)
    app = create_app(service, model_name="llama_debug")
    # Explicit knobs: 8-token pages make the 18-token system prompt span
    # 2+ cacheable pages inside the debug model's 64-token window.
    sched = PagedDecodeScheduler(
        model, params, slots=4, slot_len=64, quantum=4, page_len=8,
        prefill_chunk=16, telemetry=lambda: service.telemetry)
    service._scheduler = sched
    server, base = app.test_server()
    sys_prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1] * 2  # 18 tokens
    prompts = [sys_prompt + [10 + i] for i in range(5)]
    expect = {
        i: sequential(model, params, [p], max_new_tokens=6)[0]
        for i, p in enumerate(prompts)
    }
    errors = []
    counts = [0] * 8
    deadline = time.time() + 6.0

    def hammer(cid):
        i = cid
        while time.time() < deadline:
            i = (i + 3) % len(prompts)
            try:
                req = urllib.request.Request(
                    base + "/v1/generate",
                    data=_json.dumps({
                        "tokens": [prompts[i]], "max_new_tokens": 6,
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=60) as resp:
                    out = _json.loads(resp.read())["tokens"]
            except Exception as e:  # noqa: BLE001 — collect, fail below
                errors.append((cid, repr(e)))
                return
            if out != [expect[i]]:
                errors.append((cid, f"row mixing: prompt {i} -> {out}"))
                return
            counts[cid] += 1

    def aliasing_violations():
        snap = sched.debug_pages()
        shared, lanes = snap["shared"], list(snap["lanes"].items())
        bad = []
        for ai in range(len(lanes)):
            for bi in range(ai + 1, len(lanes)):
                overlap = (set(lanes[ai][1]) & set(lanes[bi][1])) - shared
                if overlap:
                    bad.append((lanes[ai][0], lanes[bi][0], overlap))
        return bad

    threads = [threading.Thread(target=hammer, args=(c,))
               for c in range(8)]
    try:
        for t in threads:
            t.start()
        while any(t.is_alive() for t in threads):
            # Live aliasing check.  The snapshot races the loop thread
            # (pages can be freed+reissued between reading two lanes),
            # so only a violation that SURVIVES re-reads is real.
            if aliasing_violations():
                if aliasing_violations() and aliasing_violations():
                    pytest.fail(f"page aliasing: {aliasing_violations()}")
            time.sleep(0.05)
        for t in threads:
            t.join()
    finally:
        server.shutdown()
    assert not errors, errors[:5]
    assert all(c > 0 for c in counts), counts
    stats = sched.stats()
    assert stats["admitted_total"] == stats["evicted_total"]
    assert stats["active_rows"] == 0 and stats["queued_rows"] == 0
    assert stats["prefix_hits"] > 0  # the shared prompt really shared
    _pages_balanced(stats)
    assert sched.debug_pages()["lanes"] == {}


@pytest.mark.slow
def test_serve_soak_concurrent_invariants(model_and_params):
    """Serve-soak lane (postsubmit): concurrent clients hammer the
    werkzeug app over a real socket for a bounded wall-clock.
    Invariants: no dropped requests, no cross-request row mixing
    (greedy determinism — every response must be ITS prompt's
    continuation), telemetry counters balance."""
    import json as _json
    import urllib.request

    model, params = model_and_params
    service = GenerationService(model, params)
    app = create_app(service, model_name="llama_debug")
    server, base = app.test_server()
    prompts = [[5, 9, 2], [7, 1, 4, 8], [3, 3, 3], [9], [2, 6, 4, 1, 5]]
    expect = {
        i: sequential(model, params, [p], max_new_tokens=6)[0]
        for i, p in enumerate(prompts)
    }
    errors = []
    counts = [0] * 8
    deadline = time.time() + 6.0

    def hammer(cid):
        i = cid
        while time.time() < deadline:
            i = (i + 3) % len(prompts)
            try:
                req = urllib.request.Request(
                    base + "/v1/generate",
                    data=_json.dumps({
                        "tokens": [prompts[i]], "max_new_tokens": 6,
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=60) as resp:
                    out = _json.loads(resp.read())["tokens"]
            except Exception as e:  # noqa: BLE001 — collect, fail below
                errors.append((cid, repr(e)))
                return
            if out != [expect[i]]:
                errors.append((cid, f"row mixing: prompt {i} -> {out}"))
                return
            counts[cid] += 1

    threads = [threading.Thread(target=hammer, args=(c,)) for c in range(8)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        server.shutdown()
    assert not errors, errors[:5]
    assert all(c > 0 for c in counts), counts  # every client got service
    stats = service._scheduler.stats()
    assert stats["admitted_total"] == stats["evicted_total"]
    assert stats["active_rows"] == 0 and stats["queued_rows"] == 0


# -- QoS admission: priority classes + deadlines (ISSUE 19) -------------------
#
# The activator forwards X-KFT-Priority / X-KFT-Deadline-Seconds; the
# serving layer threads them into submit().  The contract under test:
# lower class admits first (FIFO within a class), and a request whose
# deadline expired while still queued fails with DeadlineExceeded at
# selection — it must never reach prefill for a client that gave up.


def _pending(rows=((1, 2),), **kw):
    from kubeflow_tpu.models.scheduler import PendingRequest

    kw.setdefault("max_new_tokens", 2)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("top_k", None)
    kw.setdefault("eos_token", None)
    kw.setdefault("seed", 0)
    return PendingRequest([list(r) for r in rows], **kw)


def test_priority_admission_selection_order(model_and_params):
    """_next_queued as a pure unit (nothing submitted, loop parked):
    lowest priority class pops first, FIFO within a class."""
    from kubeflow_tpu.models.scheduler import PRIORITY_CLASSES

    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=2, slot_len=64, quantum=2)
    reqs = []
    for tag, cls in [("b1", "batch"), ("s1", "standard"),
                     ("i1", "interactive"), ("s2", "standard"),
                     ("b2", "batch")]:
        r = _pending(priority=PRIORITY_CLASSES[cls])
        r.tag = tag
        reqs.append(r)
    with sched._cond:
        sched._queue.extend(reqs)
    order = [sched._next_queued(pop=True).tag for _ in range(len(reqs))]
    assert order == ["i1", "s1", "s2", "b1", "b2"]
    assert sched._next_queued(pop=True) is None


def test_expired_queued_request_evicted_at_selection(model_and_params):
    from kubeflow_tpu.models.scheduler import DeadlineExceeded

    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=2, slot_len=64, quantum=2)
    dead = _pending(deadline=time.monotonic() - 0.01)
    live = _pending()
    with sched._cond:
        sched._queue.extend([dead, live])
    # Peek (the paged scheduler's mode) evicts expired requests too.
    assert sched._next_queued(pop=False) is live
    assert dead.done.is_set()
    with pytest.raises(DeadlineExceeded, match="expired while queued"):
        dead.result()
    assert sched._next_queued(pop=True) is live


def test_submit_deadline_and_priority_ride_through(model_and_params):
    from kubeflow_tpu.models.scheduler import (
        PRIORITY_CLASSES,
        DeadlineExceeded,
    )

    model, params = model_and_params
    sched = DecodeScheduler(model, params, slots=2, slot_len=64, quantum=2)
    # Already-expired deadline: fails fast with the typed error, and the
    # loop survives it (the next request is served normally).
    fut = sched.submit([[5, 9]], max_new_tokens=3,
                       deadline=time.monotonic() - 0.001)
    with pytest.raises(DeadlineExceeded):
        fut.result()
    assert sched.alive
    rows = [[5, 9, 2, 7]]
    got = sched.submit(rows, max_new_tokens=4,
                       priority=PRIORITY_CLASSES["batch"],
                       deadline=time.monotonic() + 60.0).result()
    assert got == sequential(model, params, rows, max_new_tokens=4)


def test_paged_submit_deadline_and_priority(model_and_params):
    from kubeflow_tpu.models.scheduler import (
        PRIORITY_CLASSES,
        DeadlineExceeded,
    )

    model, params = model_and_params
    sched = _paged(model, params)
    fut = sched.submit([[5, 9]], max_new_tokens=3,
                       deadline=time.monotonic() - 0.001)
    with pytest.raises(DeadlineExceeded):
        fut.result()
    assert sched.alive
    rows = [[5, 9, 2, 7]]
    got = sched.submit(rows, max_new_tokens=4,
                       priority=PRIORITY_CLASSES["interactive"],
                       deadline=time.monotonic() + 60.0).result()
    assert got == sequential(model, params, rows, max_new_tokens=4)
    _pages_balanced(sched.stats())
