import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.ops.attention import dot_product_attention, xla_attention
from kubeflow_tpu.ops.pallas import flash_attention as fa


def _qkv(b=2, s=256, h=4, kh=4, d=64, dtype=jnp.float32, seed=0):
    k0 = jax.random.key(seed)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (b, s, h, d), dtype)
    k = jax.random.normal(jax.random.fold_in(k0, 2), (b, s, kh, d), dtype)
    v = jax.random.normal(jax.random.fold_in(k0, 3), (b, s, kh, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kh", [4, 2, 1])
def test_flash_matches_reference(causal, kh):
    q, k, v = _qkv(kh=kh)
    out = fa.flash_attention(q, k, v, causal=causal)
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


def test_flash_grads_match_reference():
    q, k, v = _qkv(s=256)
    g1 = jax.grad(lambda q: fa.flash_attention(q, k, v, causal=True).sum())(q)
    g2 = jax.grad(lambda q: xla_attention(q, k, v, causal=True).sum())(q)
    assert jnp.max(jnp.abs(g1 - g2)) < 2e-4


def test_supported_gates():
    q, k, v = _qkv()
    assert fa.supported(q, k, v)
    assert not fa.supported(q, k, v, bias=jnp.zeros((1, 1, 256, 256)))
    q2, k2, v2 = _qkv(d=48)  # not 64-aligned
    assert not fa.supported(q2, k2, v2)


def test_public_op_segment_ids_block_cross_attention():
    q, k, v = _qkv(s=32)
    seg = jnp.concatenate(
        [jnp.zeros((2, 16), jnp.int32), jnp.ones((2, 16), jnp.int32)], axis=1
    )
    out = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    # Changing segment-1 values must not change segment-0 outputs.
    v2 = v.at[:, 16:].add(1.0)
    out2 = dot_product_attention(q, k, v2, segment_ids=seg, impl="xla")
    assert jnp.allclose(out[:, :16], out2[:, :16], atol=1e-6)
    assert not jnp.allclose(out[:, 16:], out2[:, 16:], atol=1e-3)


def test_bad_impl_raises():
    q, k, v = _qkv(s=32)
    with pytest.raises(ValueError):
        dot_product_attention(q, k, v, impl="cuda")
