import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.ops.attention import dot_product_attention, xla_attention
from kubeflow_tpu.ops.pallas import flash_attention as fa


def _qkv(b=2, s=256, h=4, kh=4, d=64, dtype=jnp.float32, seed=0):
    k0 = jax.random.key(seed)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (b, s, h, d), dtype)
    k = jax.random.normal(jax.random.fold_in(k0, 2), (b, s, kh, d), dtype)
    v = jax.random.normal(jax.random.fold_in(k0, 3), (b, s, kh, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kh", [4, 2, 1])
def test_flash_matches_reference(causal, kh):
    q, k, v = _qkv(kh=kh)
    out = fa.flash_attention(q, k, v, causal=causal)
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kh", [4, 2])
def test_flash_grads_match_reference(causal, kh):
    # The blocked Pallas backward (dq + dk/dv kernels) against XLA's vjp;
    # covers GQA group-summed dk/dv and the causal block-skip paths.
    q, k, v = _qkv(s=512, kh=kh)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    flash = loss(lambda q, k, v: fa.flash_attention(q, k, v, causal=causal))
    ref = loss(lambda q, k, v: xla_attention(q, k, v, causal=causal))
    gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        scale = jnp.max(jnp.abs(b)) + 1e-9
        assert jnp.max(jnp.abs(a - b)) / scale < 1e-4


def test_flash_fwd_lse_residual_layout():
    # lse residual layout: forward-with-residuals returns [b, h, s, 128].
    q, k, v = _qkv(s=256)
    out, lse = fa._flash_fwd(
        q, k, v, causal=True, softmax_scale=None, block_q=256, block_k=256,
        interpret=True, return_residuals=True,
    )
    assert lse.shape == (2, 4, 256, 128)
    # Lane-replication: every lane carries the same per-row value.
    assert jnp.allclose(lse[..., 0], lse[..., 64], atol=1e-6)


def test_supported_gates():
    q, k, v = _qkv()
    assert fa.supported(q, k, v)
    assert not fa.supported(q, k, v, bias=jnp.zeros((1, 1, 256, 256)))
    q2, k2, v2 = _qkv(d=48)  # not 64-aligned
    assert not fa.supported(q2, k2, v2)


def _packed_segments(b=2, s=256, n_docs=4):
    """Packed-training-style ids: n_docs contiguous documents per row
    (ids 1..n_docs, the data/packing.py convention, no pad here)."""
    import numpy as np

    ids = np.repeat(np.arange(1, n_docs + 1), s // n_docs)
    return jnp.asarray(np.tile(ids, (b, 1)), jnp.int32)


def test_supported_admits_packed_and_cross_length_shapes():
    """ISSUE 7 acceptance: segment_ids (the packed-training path —
    llama.py threads test_packing.py's ids here) and end-aligned causal
    sq<sk (ragged prefill) are kernel shapes now."""
    q, k, v = _qkv()
    seg = _packed_segments()
    assert fa.supported(q, k, v, segment_ids=seg, causal=True)
    assert fa.supported(q, k, v, segment_ids=seg)
    # One id vector describes both sides: cross-length + segments stays XLA.
    qs, ks, vs = _qkv(s=128)
    assert not fa.supported(qs, k, v, segment_ids=seg)
    # Non-integer ids are not a segment mask.
    assert not fa.supported(q, k, v, segment_ids=seg.astype(jnp.float32))
    # Cross-length: causal needs sq <= sk (end-aligned); non-causal is free.
    assert fa.supported(qs, k, v, causal=True)
    assert fa.supported(qs, k, v)
    assert not fa.supported(q, ks, vs, causal=True)  # sq > sk
    assert fa.supported(q, ks, vs)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("kh", [4, 2])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seg", [False, True])
def test_flash_parity_matrix(seg, causal, kh, dtype):
    """Flash-vs-XLA parity (interpret mode on CPU): segment_ids × causal ×
    GQA × dtype, forward AND grads — the coverage grid ISSUE 7 widened the
    kernel into."""
    q, k, v = _qkv(b=1, kh=kh, dtype=dtype, seed=7)
    segment_ids = _packed_segments(b=1) if seg else None
    fwd_tol, grad_tol = (2e-5, 2e-4) if dtype == jnp.float32 else (3e-2, 3e-2)

    out = fa.flash_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    ref = xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    assert out.dtype == dtype
    assert jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))) < fwd_tol

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    gf = jax.grad(loss(lambda q, k, v: fa.flash_attention(
        q, k, v, causal=causal, segment_ids=segment_ids)), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(lambda q, k, v: xla_attention(
        q, k, v, causal=causal, segment_ids=segment_ids)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        a, b = a.astype(jnp.float32), b.astype(jnp.float32)
        scale = jnp.max(jnp.abs(b)) + 1e-9
        assert jnp.max(jnp.abs(a - b)) / scale < grad_tol


@pytest.mark.parametrize("causal", [False, True])
def test_flash_cross_length_matches_reference(causal):
    """sq < sk (ragged prefill / decode-style): end-aligned causal offset
    k = sk - sq, identical to xla_attention's tril convention — fwd + vjp."""
    b, sq, sk, h, d = 2, 128, 256, 4, 64
    k0 = jax.random.key(11)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (b, sq, h, d))
    k = jax.random.normal(jax.random.fold_in(k0, 2), (b, sk, h, d))
    v = jax.random.normal(jax.random.fold_in(k0, 3), (b, sk, h, d))
    out = fa.flash_attention(q, k, v, causal=causal)
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    gf = jax.grad(loss(lambda q, k, v: fa.flash_attention(q, k, v, causal=causal)),
                  argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(lambda q, k, v: xla_attention(q, k, v, causal=causal)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, bb in zip(gf, gx):
        scale = jnp.max(jnp.abs(bb)) + 1e-9
        assert jnp.max(jnp.abs(a - bb)) / scale < 1e-4


def test_auto_routes_packed_segments_through_flash(monkeypatch):
    """impl="auto" on (mocked) TPU now takes the Pallas path for the
    packed-training shape — the routing ISSUE 7 unlocked.  On CPU the
    kernel runs in interpret mode, so the routed result must still match
    the XLA reference."""
    q, k, v = _qkv(b=1)
    seg = _packed_segments(b=1)
    # Patch only the routing answer — the kernel itself still sees the
    # real (cpu) platform, so it runs in interpret mode.
    monkeypatch.setattr(fa, "should_use",
                        lambda q, k=None, **kw: True)
    out = dot_product_attention(q, k, v, causal=True, segment_ids=seg,
                                impl="auto")
    ref = xla_attention(q, k, v, causal=True, segment_ids=seg)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


def test_xla_attention_masking_is_allocation_free():
    """Regression for the BENCH_r05 O(S²) allocation: the causal (and
    segment) masking must be built from rank-4 iota comparisons fused
    into the select — NO standalone 2-D [sq, sk] mask array (the
    jnp.tril(jnp.ones((sq, sk))) literal) anywhere in the jaxpr, and no
    multi-dim constants baked in."""
    s = 512
    q = jnp.zeros((1, s, 2, 64), jnp.float32)
    seg = jnp.zeros((1, s), jnp.int32)

    def eqns(jaxpr):
        from jax._src import core as jcore

        for eqn in jaxpr.eqns:
            yield eqn
            for val in eqn.params.values():
                for sub in (val if isinstance(val, (tuple, list)) else [val]):
                    if isinstance(sub, jcore.ClosedJaxpr):
                        yield from eqns(sub.jaxpr)
                    elif isinstance(sub, jcore.Jaxpr):
                        yield from eqns(sub)

    for kwargs in ({"causal": True}, {"causal": True, "segment_ids": seg},
                   {"segment_ids": seg}):
        closed = jax.make_jaxpr(
            lambda q, k, v: xla_attention(q, k, v, **kwargs))(q, q, q)
        # No O(S²)-sized constant may be baked into the computation (the
        # closed-over segment_ids vector is O(S) and fine).
        assert all(getattr(c, "size", 0) < s * s for c in closed.consts)
        for eqn in eqns(closed.jaxpr):
            for var in eqn.outvars:
                shape = tuple(getattr(var.aval, "shape", ()))
                assert shape != (s, s), (
                    f"standalone 2-D [sq, sk] mask buffer from "
                    f"{eqn.primitive}: the tril path is back")


def test_should_use_is_footprint_aware(monkeypatch):
    """Routing consults attention_footprint_bytes against free HBM: a
    short sequence whose masked-XLA footprint would blow the budget now
    routes to flash; with plentiful HBM the measured seq crossover
    decides; CPU never routes to the kernel."""
    from kubeflow_tpu.telemetry import compute as ctel

    q = jnp.zeros((2, 512, 8, 64))  # footprint 2·4·2·8·512² = 16.8 MB
    assert not fa.should_use(q, q, causal=True)  # CPU: always XLA
    monkeypatch.setattr(fa, "_platform", lambda: "tpu")
    monkeypatch.setattr(ctel, "free_hbm_bytes", lambda: None)
    assert not fa.should_use(q, q, causal=True)  # no stats: seq cutoff
    monkeypatch.setattr(ctel, "free_hbm_bytes", lambda: 16 * 2**20)
    assert fa.should_use(q, q, causal=True)      # over budget: flash
    monkeypatch.setattr(ctel, "free_hbm_bytes", lambda: 2**40)
    assert not fa.should_use(q, q, causal=True)  # fits comfortably: XLA
    big = jnp.zeros((1, 1024, 1, 64))
    assert fa.should_use(big, big)               # crossover always flash


def test_flash_block_env_overrides(monkeypatch):
    """KFT_FLASH_BLOCK_Q/K override the block heuristic (sweep knob):
    alignment violations raise (always-illegal typo), while a sequence
    the override does not divide falls back to the heuristic for that
    call — the knob is process-global and must not crash other
    auto-routed shapes in the same process."""
    monkeypatch.setenv("KFT_FLASH_BLOCK_Q", "128")
    monkeypatch.setenv("KFT_FLASH_BLOCK_K", "256")
    assert fa.default_blocks(1024, 1024) == (128, 256)
    monkeypatch.setenv("KFT_FLASH_BLOCK_K", "512")
    assert fa.default_blocks(1024, 1024) == (128, 512)
    with pytest.raises(ValueError, match="KFT_FLASH_BLOCK_Q"):
        monkeypatch.setenv("KFT_FLASH_BLOCK_Q", "100")  # % 8 != 0
        fa.default_blocks(1024, 1024)
    with pytest.raises(ValueError, match="KFT_FLASH_BLOCK_K"):
        monkeypatch.setenv("KFT_FLASH_BLOCK_Q", "128")
        monkeypatch.setenv("KFT_FLASH_BLOCK_K", "192")  # % 128 != 0
        fa.default_blocks(1024, 1024)
    # 1024 % 384 != 0: the sweep knob doesn't fit THIS shape — heuristic
    # fallback per axis, no crash (the other axis keeps its override).
    monkeypatch.setenv("KFT_FLASH_BLOCK_Q", "384")
    monkeypatch.setenv("KFT_FLASH_BLOCK_K", "512")
    assert fa.default_blocks(1024, 1024) == (256, 512)
    monkeypatch.delenv("KFT_FLASH_BLOCK_Q")
    monkeypatch.delenv("KFT_FLASH_BLOCK_K")
    assert fa.default_blocks(8192, 8192) == (1024, 1024)  # heuristic back


def test_public_op_segment_ids_block_cross_attention():
    q, k, v = _qkv(s=32)
    seg = jnp.concatenate(
        [jnp.zeros((2, 16), jnp.int32), jnp.ones((2, 16), jnp.int32)], axis=1
    )
    out = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    # Changing segment-1 values must not change segment-0 outputs.
    v2 = v.at[:, 16:].add(1.0)
    out2 = dot_product_attention(q, k, v2, segment_ids=seg, impl="xla")
    assert jnp.allclose(out[:, :16], out2[:, :16], atol=1e-6)
    assert not jnp.allclose(out[:, 16:], out2[:, 16:], atol=1e-3)


def test_bad_impl_raises():
    q, k, v = _qkv(s=32)
    with pytest.raises(ValueError):
        dot_product_attention(q, k, v, impl="cuda")


# -- pallas rms_norm (ops/pallas/rms_norm.py, interpret mode on CPU) ----------


@pytest.mark.parametrize("kv_h", [2, 4])  # GQA (g=2) and MHA (g=1)
def test_flash_decode_matches_reference(kv_h):
    from kubeflow_tpu.ops.pallas import flash_decode as fd

    b, S, h, d = 2, 256, 4, 64
    rng = jax.random.key(0)
    q = jax.random.normal(rng, (b, 1, h, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, S, kv_h, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, S, kv_h, d))
    # Mask the tail (unwritten cache slots) differently per row.
    valid = jnp.arange(S)[None, :] < jnp.array([[100], [256]])
    rows = jnp.where(valid, 0.0, -1e30).astype(jnp.float32)
    assert fd.supported(q, k, v, bias_rows=rows)
    out = fd.flash_decode(q, k, v, rows)
    ref = xla_attention(q, k, v, bias=rows[:, None, None, :])
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_decode_no_bias_and_scale():
    from kubeflow_tpu.ops.pallas import flash_decode as fd

    b, S, h, d = 1, 128, 2, 64
    rng = jax.random.key(3)
    q = jax.random.normal(rng, (b, 1, h, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, S, h, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, S, h, d))
    out = fd.flash_decode(q, k, v, softmax_scale=0.5)
    ref = xla_attention(q, k, v, softmax_scale=0.5)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_decode_supported_gates():
    from kubeflow_tpu.ops.pallas import flash_decode as fd

    q = jnp.zeros((2, 1, 4, 64))
    k = v = jnp.zeros((2, 256, 2, 64))
    assert fd.supported(q, k, v)
    assert not fd.supported(jnp.zeros((2, 2, 4, 64)), k, v)  # s != 1
    k200 = jnp.zeros((2, 200, 2, 64))
    assert not fd.supported(q, k200, k200)  # S has no block size
    q12 = jnp.zeros((2, 1, 4, 12))
    k12 = jnp.zeros((2, 256, 2, 12))
    assert not fd.supported(q12, k12, k12)  # d % 8
    assert not fd.supported(q, k, v, bias_rows=jnp.zeros((2, 128)))
    # dS-major (model cache layout) gate.
    kds = jnp.zeros((2, 2, 64, 256))
    assert fd.supported(q, kds, kds, ds_major=True)


def test_generate_via_flash_decode_matches_xla(monkeypatch):
    """End-to-end: generation with the decode kernel (forced via env,
    interpret mode on CPU) matches the XLA path token-for-token."""
    import dataclasses

    from kubeflow_tpu.models.generate import generate
    from kubeflow_tpu.models.llama import CONFIGS, Llama

    cfg = dataclasses.replace(
        CONFIGS["llama_debug"], dim=256, n_heads=4, n_kv_heads=2,
        ffn_dim=256, max_seq_len=128,
    )
    prompt = jax.random.randint(jax.random.key(5), (2, 64), 0, 256)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), prompt)["params"]
    # prompt 64 + 64 new = cache 128, divisible: kernel path active.
    xla_out = generate(model, params, prompt, max_new_tokens=64)
    monkeypatch.setenv("KUBEFLOW_TPU_FORCE_FLASH_DECODE", "1")
    jax.clear_caches()  # the env gate is baked in at trace time
    fd_out = generate(model, params, prompt, max_new_tokens=64)
    assert (xla_out == fd_out).all()


def test_pallas_rms_norm_matches_xla():
    import numpy as np

    from kubeflow_tpu import ops

    rng = jax.random.key(0)
    x = jax.random.normal(rng, (4, 96, 256), jnp.float32)
    scale = jax.random.normal(jax.random.key(1), (256,)) + 1.0
    want = ops.rms_norm(x, scale, impl="xla")
    got = ops.rms_norm(x, scale, impl="pallas")
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               atol=1e-5, rtol=1e-5)


def test_pallas_rms_norm_grads_match():
    import numpy as np

    from kubeflow_tpu import ops

    x = jax.random.normal(jax.random.key(2), (8, 384), jnp.float32)
    scale = jax.random.normal(jax.random.key(3), (384,)) + 1.0

    def loss(impl):
        def fn(x, scale):
            y = ops.rms_norm(x, scale, impl=impl)
            return (y * jnp.sin(y)).sum()
        return fn

    gx_w, gs_w = jax.grad(loss("xla"), argnums=(0, 1))(x, scale)
    gx_g, gs_g = jax.grad(loss("pallas"), argnums=(0, 1))(x, scale)
    np.testing.assert_allclose(np.asarray(gx_w), np.asarray(gx_g),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gs_w), np.asarray(gs_g),
                               atol=1e-4, rtol=1e-4)


def test_pallas_rms_norm_odd_rows_and_bf16():
    import numpy as np

    from kubeflow_tpu import ops

    # 13 rows forces tile padding; bf16 exercises the dtype round-trip.
    x = jax.random.normal(jax.random.key(4), (13, 128), jnp.bfloat16)
    scale = jnp.ones((128,))
    want = ops.rms_norm(x, scale, impl="xla")
    got = ops.rms_norm(x, scale, impl="pallas")
    np.testing.assert_allclose(
        np.asarray(want, np.float32), np.asarray(got, np.float32),
        atol=1e-2, rtol=1e-2,
    )


def test_pallas_rms_norm_rejects_unaligned():
    import pytest as _pytest

    from kubeflow_tpu import ops

    with _pytest.raises(ValueError, match="128"):
        ops.rms_norm(jnp.ones((4, 100)), jnp.ones((100,)), impl="pallas")


def test_default_blocks_heuristic():
    from kubeflow_tpu.ops.pallas.flash_attention import default_blocks

    assert default_blocks(8192, 8192) == (1024, 1024)
    assert default_blocks(4096, 4096) == (512, 512)
    assert default_blocks(2048, 2048) == (256, 256)
    assert default_blocks(256, 256) == (256, 256)
    # Non-power-of-two lengths still divide their blocks.
    bq, bk = default_blocks(3072, 3072)
    assert 3072 % bq == 0 and 3072 % bk == 0
    # Ragged lengths fall back to exactly the legacy defaults, so the
    # supported() gate (which checks those) keeps its meaning: shapes it
    # rejects never reach the kernel with any block size.
    assert default_blocks(640, 640) == (256, 256)


def test_default_blocks_respect_kernel_alignment_for_all_supported_seqs():
    """Every length supported() admits must get kernel-legal auto blocks:
    bq % 8 == 0, bk % 128 == 0 (TPU sublane/lane constraints), and both
    dividing the sequence.  Regression for 2560/3584/4608-style lengths
    where sk // 8 is a multiple of 32 but not of 128."""
    from kubeflow_tpu.ops.pallas.flash_attention import default_blocks

    # supported() requires sq % bq == 0 / sk % bk == 0 at the floor blocks,
    # i.e. multiples of 256 (plus short seqs equal to smaller lane-legal
    # sizes, which take the floor fallback anyway).
    for s in range(256, 32768 + 1, 256):
        bq, bk = default_blocks(s, s)
        assert bq % 8 == 0, (s, bq)
        assert bk % 128 == 0, (s, bk)
        assert s % bq == 0 and s % bk == 0, (s, bq, bk)
    # The specific advisor shapes: scaled-and-rounded when that divides,
    # floor fallback otherwise — never an unaligned block.
    assert default_blocks(2560, 2560) == (320, 256)
    # Per-axis fallback: bq=448 is legal even though bk falls back (384
    # does not divide 3584).
    assert default_blocks(3584, 3584) == (448, 256)
    assert default_blocks(4608, 4608) == (576, 512)


@pytest.mark.slow
def test_flash_matches_xla_at_auto_block_sizes():
    """Exactness at a length where the heuristic picks 512-wide tiles (the
    block study changed the default; the math must not change with it)."""
    from kubeflow_tpu.ops.attention import xla_attention
    from kubeflow_tpu.ops.pallas.flash_attention import flash_attention

    B, S, H, D = 1, 4096, 1, 64
    q = jax.random.normal(jax.random.key(0), (B, S, H, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, S, H, D), jnp.float32)
    out = flash_attention(q, k, v, causal=True)  # auto: 512x512
    ref = xla_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 2e-3
