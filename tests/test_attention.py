import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.ops.attention import dot_product_attention, xla_attention
from kubeflow_tpu.ops.pallas import flash_attention as fa


def _qkv(b=2, s=256, h=4, kh=4, d=64, dtype=jnp.float32, seed=0):
    k0 = jax.random.key(seed)
    q = jax.random.normal(jax.random.fold_in(k0, 1), (b, s, h, d), dtype)
    k = jax.random.normal(jax.random.fold_in(k0, 2), (b, s, kh, d), dtype)
    v = jax.random.normal(jax.random.fold_in(k0, 3), (b, s, kh, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kh", [4, 2, 1])
def test_flash_matches_reference(causal, kh):
    q, k, v = _qkv(kh=kh)
    out = fa.flash_attention(q, k, v, causal=causal)
    ref = xla_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kh", [4, 2])
def test_flash_grads_match_reference(causal, kh):
    # The blocked Pallas backward (dq + dk/dv kernels) against XLA's vjp;
    # covers GQA group-summed dk/dv and the causal block-skip paths.
    q, k, v = _qkv(s=512, kh=kh)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v) ** 2).sum()

    flash = loss(lambda q, k, v: fa.flash_attention(q, k, v, causal=causal))
    ref = loss(lambda q, k, v: xla_attention(q, k, v, causal=causal))
    gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        scale = jnp.max(jnp.abs(b)) + 1e-9
        assert jnp.max(jnp.abs(a - b)) / scale < 1e-4


def test_flash_fwd_lse_residual_layout():
    # lse residual layout: forward-with-residuals returns [b, h, s, 128].
    q, k, v = _qkv(s=256)
    out, lse = fa._flash_fwd(
        q, k, v, causal=True, softmax_scale=None, block_q=256, block_k=256,
        interpret=True, return_residuals=True,
    )
    assert lse.shape == (2, 4, 256, 128)
    # Lane-replication: every lane carries the same per-row value.
    assert jnp.allclose(lse[..., 0], lse[..., 64], atol=1e-6)


def test_supported_gates():
    q, k, v = _qkv()
    assert fa.supported(q, k, v)
    assert not fa.supported(q, k, v, bias=jnp.zeros((1, 1, 256, 256)))
    q2, k2, v2 = _qkv(d=48)  # not 64-aligned
    assert not fa.supported(q2, k2, v2)


def test_public_op_segment_ids_block_cross_attention():
    q, k, v = _qkv(s=32)
    seg = jnp.concatenate(
        [jnp.zeros((2, 16), jnp.int32), jnp.ones((2, 16), jnp.int32)], axis=1
    )
    out = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
    # Changing segment-1 values must not change segment-0 outputs.
    v2 = v.at[:, 16:].add(1.0)
    out2 = dot_product_attention(q, k, v2, segment_ids=seg, impl="xla")
    assert jnp.allclose(out[:, :16], out2[:, :16], atol=1e-6)
    assert not jnp.allclose(out[:, 16:], out2[:, 16:], atol=1e-3)


def test_bad_impl_raises():
    q, k, v = _qkv(s=32)
    with pytest.raises(ValueError):
        dot_product_attention(q, k, v, impl="cuda")


# -- pallas rms_norm (ops/pallas/rms_norm.py, interpret mode on CPU) ----------


def test_pallas_rms_norm_matches_xla():
    import numpy as np

    from kubeflow_tpu import ops

    rng = jax.random.key(0)
    x = jax.random.normal(rng, (4, 96, 256), jnp.float32)
    scale = jax.random.normal(jax.random.key(1), (256,)) + 1.0
    want = ops.rms_norm(x, scale, impl="xla")
    got = ops.rms_norm(x, scale, impl="pallas")
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               atol=1e-5, rtol=1e-5)


def test_pallas_rms_norm_grads_match():
    import numpy as np

    from kubeflow_tpu import ops

    x = jax.random.normal(jax.random.key(2), (8, 384), jnp.float32)
    scale = jax.random.normal(jax.random.key(3), (384,)) + 1.0

    def loss(impl):
        def fn(x, scale):
            y = ops.rms_norm(x, scale, impl=impl)
            return (y * jnp.sin(y)).sum()
        return fn

    gx_w, gs_w = jax.grad(loss("xla"), argnums=(0, 1))(x, scale)
    gx_g, gs_g = jax.grad(loss("pallas"), argnums=(0, 1))(x, scale)
    np.testing.assert_allclose(np.asarray(gx_w), np.asarray(gx_g),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gs_w), np.asarray(gs_g),
                               atol=1e-4, rtol=1e-4)


def test_pallas_rms_norm_odd_rows_and_bf16():
    import numpy as np

    from kubeflow_tpu import ops

    # 13 rows forces tile padding; bf16 exercises the dtype round-trip.
    x = jax.random.normal(jax.random.key(4), (13, 128), jnp.bfloat16)
    scale = jnp.ones((128,))
    want = ops.rms_norm(x, scale, impl="xla")
    got = ops.rms_norm(x, scale, impl="pallas")
    np.testing.assert_allclose(
        np.asarray(want, np.float32), np.asarray(got, np.float32),
        atol=1e-2, rtol=1e-2,
    )


def test_pallas_rms_norm_rejects_unaligned():
    import pytest as _pytest

    from kubeflow_tpu import ops

    with _pytest.raises(ValueError, match="128"):
        ops.rms_norm(jnp.ones((4, 100)), jnp.ones((100,)), impl="pallas")
