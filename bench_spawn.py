#!/usr/bin/env python3
"""Platform half of the BASELINE.md metric pair: notebook spawn-to-ready.

Measures the control-plane path (spawner POST -> reconcile -> webhook
admission -> status converged) over N iterations on the in-memory API
server; image pull and kubelet start are simulated (those costs belong to
the image-size work, images/README.md).  Prints ONE JSON line in the same
shape as bench.py.
"""
from __future__ import annotations

import json
import statistics
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from ci.e2e import E2E

ITERATIONS = 10
# Control-plane spawn-to-ready on the in-memory API server.  Round-1
# established 0.046 s with the e2e poller sleeping 20 ms per probe — most
# of that number was the measurement's own poll quantization (the r02
# "regression" to 0.0492 was quantization noise, not the workqueue change
# it was attributed to).  Round 3 sharpened the poller to 2 ms, showing
# the actual path at 10-12 ms min across sessions, and re-baselined at
# the upper edge of that band on the MIN estimator: vs_baseline < 1.0
# means a real regression, 1.0-1.3 the established band.  Round 5: the
# fleet-scale informer work (cache-backed pod/STS/event reads in
# reconcile — BASELINE.md "Control-plane fleet scale") measured 8.7 ms
# on a quiet host (vs_baseline 1.5), but the same code reads 13 ms under
# concurrent CPU load — the metric is host-contention-sensitive at this
# scale, so the constant STAYS at the contention-tolerant 0.013 rather
# than chasing the quiet-host best into false regressions.
BASELINE_SPAWN_S = 0.013


def main() -> int:
    latencies = []
    e2e = E2E()
    try:
        ns = e2e.register()
        for i in range(ITERATIONS):
            name = f"bench-nb-{i}"
            latencies.append(e2e.spawn(ns, name))
            e2e.delete(ns, name)
    finally:
        e2e.close()

    median = statistics.median(latencies)
    best = min(latencies)
    # The min is the stable estimator of the path itself (same rationale as
    # bench.py's best window): at the 10 ms scale, host-scheduler noise
    # lands only in the upper quantiles.  The metric is NAMED for the min
    # estimator (advisor r3): round 3 silently switched `value` from median
    # to min under the old name, which read as a bogus 3.5x improvement —
    # the rename marks the series discontinuity explicitly, and the median
    # stays on the line for consumers tracking the old series.
    vs = 1.0 if BASELINE_SPAWN_S is None else BASELINE_SPAWN_S / best
    print(
        json.dumps(
            {
                "metric": "notebook_spawn_to_ready_min_s",
                "value": round(best, 4),
                "unit": "seconds",
                "vs_baseline": round(vs, 4),
                "value_median": round(median, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
