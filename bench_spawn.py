#!/usr/bin/env python3
"""Platform half of the BASELINE.md metric pair: notebook spawn-to-ready.

Measures the control-plane path (spawner POST -> reconcile -> webhook
admission -> status converged) over N iterations on the in-memory API
server; image pull and kubelet start are simulated (those costs belong to
the image-size work, images/README.md).  Prints ONE JSON line in the same
shape as bench.py.
"""
from __future__ import annotations

import json
import statistics
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

from ci.e2e import E2E

ITERATIONS = 10
# Control-plane spawn-to-ready established at round 1 on this harness
# (median of 10, in-memory API server; BASELINE.md).
BASELINE_SPAWN_S = 0.046


def main() -> int:
    latencies = []
    e2e = E2E()
    try:
        ns = e2e.register()
        for i in range(ITERATIONS):
            name = f"bench-nb-{i}"
            latencies.append(e2e.spawn(ns, name))
            e2e.delete(ns, name)
    finally:
        e2e.close()

    median = statistics.median(latencies)
    vs = 1.0 if BASELINE_SPAWN_S is None else BASELINE_SPAWN_S / median
    print(
        json.dumps(
            {
                "metric": "notebook_spawn_to_ready_s",
                "value": round(median, 4),
                "unit": "seconds",
                "vs_baseline": round(vs, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
