"""Focused interleaved A/B: 1024x1024 vs 512x1024 at the 1b4 shape."""
import json, time
import jax, jax.numpy as jnp
from kubeflow_tpu.ops.pallas.flash_attention import flash_attention

B, H, S, D = 1, 16, 8192, 128
rng = jax.random.key(0)
q = jax.random.normal(jax.random.fold_in(rng, 0), (B, S, H, D), jnp.bfloat16)
k = jax.random.normal(jax.random.fold_in(rng, 1), (B, S, H, D), jnp.bfloat16)
v = jax.random.normal(jax.random.fold_in(rng, 2), (B, S, H, D), jnp.bfloat16)

def make_step(bq, bk):
    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

steps = {c: make_step(*c) for c in [(1024, 1024), (512, 1024)]}
for g in steps.values():
    out = g(q, k, v); float(jnp.sum(out[0].astype(jnp.float32)))
times = {c: [] for c in steps}
for r in range(14):
    for c, g in steps.items():
        t0 = time.perf_counter()
        for _ in range(10):
            out = g(q, k, v)
        float(jnp.sum(out[0].astype(jnp.float32)))
        times[c].append((time.perf_counter() - t0) / 10)
for c, ts in times.items():
    ts.sort()
    print(json.dumps({"cfg": list(c), "min_ms": round(ts[0]*1e3, 2),
                      "p25_ms": round(ts[len(ts)//4]*1e3, 2),
                      "med_ms": round(ts[len(ts)//2]*1e3, 2)}), flush=True)
