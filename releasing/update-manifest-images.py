#!/usr/bin/env python3
"""Pin manifest image tags to a release version (role of reference
releasing/update-manifests-images): rewrites `:latest` on
ghcr.io/kubeflow-tpu images in manifests/ to the tag in releasing/VERSION
(or --tag).
"""
from __future__ import annotations

import argparse
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
IMAGE_RE = re.compile(r"(ghcr\.io/kubeflow-tpu/[\w.-]+):[\w.-]+")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tag", default=None)
    ap.add_argument("--check", action="store_true",
                    help="fail if any :latest remains (release gate)")
    args = ap.parse_args(argv)
    tag = args.tag or (ROOT / "releasing" / "VERSION").read_text().strip()

    changed = 0
    for path in sorted((ROOT / "manifests").rglob("*.yaml")):
        text = path.read_text()
        new = IMAGE_RE.sub(rf"\1:{tag}", text)
        if new != text:
            path.write_text(new)
            changed += 1
            print(f"pinned images in {path.relative_to(ROOT)} -> {tag}")
    if args.check:
        stale = [
            str(p.relative_to(ROOT))
            for p in (ROOT / "manifests").rglob("*.yaml")
            if ":latest" in p.read_text()
        ]
        if stale:
            print("ERROR: :latest images remain in", ", ".join(stale))
            return 1
    print(f"{changed} file(s) updated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
