#!/usr/bin/env python3
"""Platform conformance suite.

The reference ships a conformance program that runs each component's
conformance job in-cluster and collects pass/fail reports (reference
conformance/1.5/README.md:1-27, kfp-conformance.yaml).  This is the same
contract for the TPU-native platform: a fixed list of named checks, each
asserting an end-user-visible behavior contract (not an implementation
detail), producing a machine-readable report.

Run:  python conformance/run.py [--report PATH]
Exit: 0 iff every check passed; report JSON always written.

The suite drives the real control plane (controllers with live watch
threads, the admission webhook over HTTP, the web apps over WSGI) against
the in-memory API server, so it runs hermetically in CI; pointing it at a
real cluster only requires swapping the client factory.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHECKS = []


def check(name):
    def wrap(fn):
        CHECKS.append((name, fn))
        return fn
    return wrap


def _e2e(**kwargs):
    from ci.e2e import E2E

    return E2E(**kwargs)


@check("notebook-spawn-lifecycle")
def spawn_lifecycle():
    """Register → spawn → Ready → stop/start → delete (SURVEY §3.1)."""
    e2e = _e2e()
    try:
        ns = e2e.register()
        e2e.spawn(ns)
        e2e.stop_start(ns)
        e2e.delete(ns)
    finally:
        e2e.close()


@check("multi-host-slice")
def multi_host_slice():
    """A multi-host topology spawns hosts(topology) workers with stable DNS
    and per-worker TPU env — the platform's defining TPU capability."""
    from kubeflow_tpu.platform.k8s.types import SERVICE, STATEFULSET, deep_get

    e2e = _e2e()
    try:
        e2e.kube.add_tpu_node("tpu-multi-1", topology="4x4")
        ns = e2e.register()
        resp = e2e.jupyter.post(
            f"/api/namespaces/{ns}/notebooks",
            json={"name": "slice-nb",
                  "tpus": {"accelerator": "v5e", "topology": "4x4"}},
            headers=e2e.user,
        )
        assert resp.status_code == 200, resp.get_data(as_text=True)
        sts = e2e._wait(
            lambda: e2e._get(STATEFULSET, "slice-nb", ns), "statefulset"
        )
        replicas = deep_get(sts, "spec", "replicas")
        assert replicas == 2, f"v5e 4x4 = 16 chips / 8 per host: {replicas}"
        env = {e.get("name"): e for e in deep_get(
            sts, "spec", "template", "spec", "containers",
            default=[{}])[0].get("env", [])}
        for key in ("TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES", "TPU_TOPOLOGY"):
            assert key in env, f"missing {key}"
        headless = e2e._wait(
            lambda: e2e._get(SERVICE, "slice-nb-workers", ns), "headless svc"
        )
        assert deep_get(headless, "spec", "clusterIP") == "None"
        assert deep_get(headless, "spec", "publishNotReadyAddresses") is True
    finally:
        e2e.close()


@check("multislice-dcn")
def multislice_dcn():
    """spec.tpu.slices spawns one StatefulSet per ICI slice with per-slice
    libtpu bootstrap env and MEGASCALE cross-slice identity, all behind one
    headless service — the GKE-multislice contract.  Driven at the spawner
    config's full ceiling (maxSlices: 4 — VERDICT r4 item 7: every
    executed multislice path had been 2-slice)."""
    from kubeflow_tpu.platform.k8s.types import (
        PODDISRUPTIONBUDGET, STATEFULSET, deep_get,
    )

    e2e = _e2e()
    try:
        e2e.kube.add_tpu_node("tpu-ms-1", topology="4x4")
        ns = e2e.register()
        slices = 4  # spawner_ui_config.yaml tpus.maxSlices
        resp = e2e.jupyter.post(
            f"/api/namespaces/{ns}/notebooks",
            json={"name": "ms-nb",
                  "tpus": {"accelerator": "v5e", "topology": "4x4",
                           "slices": slices}},
            headers=e2e.user,
        )
        assert resp.status_code == 200, resp.get_data(as_text=True)
        sts_names = ["ms-nb"] + [f"ms-nb-s{i}" for i in range(1, slices)]
        for idx, sts_name in enumerate(sts_names):
            sts = e2e._wait(
                lambda n=sts_name: e2e._get(STATEFULSET, n, ns), sts_name
            )
            assert deep_get(sts, "spec", "replicas") == 2, sts_name
            env = {e.get("name"): e.get("value") for e in deep_get(
                sts, "spec", "template", "spec", "containers",
                default=[{}])[0].get("env", [])}
            assert env.get("MEGASCALE_SLICE_ID") == str(idx)
            assert env.get("MEGASCALE_NUM_SLICES") == str(slices)
            hosts = (env.get("TPU_WORKER_HOSTNAMES") or "").split(",")
            assert len(hosts) == 2 and all(
                h.startswith(f"{sts_name}-") for h in hosts
            ), hosts
        pdb = e2e._wait(
            lambda: e2e._get(PODDISRUPTIONBUDGET, "ms-nb-slice", ns), "pdb"
        )
        # All workers of all slices: 2 hosts x 4 slices.
        assert deep_get(pdb, "spec", "minAvailable") == 2 * slices
    finally:
        e2e.close()


@check("multislice-stop-cull")
def multislice_stop_cull():
    """Multislice lifecycle contracts (VERDICT r3 item 8), end-user level:
    the UI Service routes to slice-0 worker-0 only (pod-name selector), a
    culler pass probes exactly that Service URL and culls the notebook
    WHOLE — the stop annotation scales EVERY slice StatefulSet to 0 in one
    reconcile — and start restores every slice (reference stop semantics
    notebook_controller.go:362-365, extended to slices)."""
    from kubeflow_tpu.platform.controllers.culling import CullingReconciler
    from kubeflow_tpu.platform.k8s.types import (
        NOTEBOOK, SERVICE, STATEFULSET, deep_get,
    )
    from kubeflow_tpu.platform.runtime import Request

    e2e = _e2e()
    try:
        e2e.kube.add_tpu_node("tpu-msc-1", topology="4x4")
        ns = e2e.register()
        resp = e2e.jupyter.post(
            f"/api/namespaces/{ns}/notebooks",
            json={"name": "msc-nb",
                  "tpus": {"accelerator": "v5e", "topology": "4x4",
                           "slices": 2}},
            headers=e2e.user,
        )
        assert resp.status_code == 200, resp.get_data(as_text=True)
        slice_stses = ("msc-nb", "msc-nb-s1")
        for sts_name in slice_stses:
            sts = e2e._wait(
                lambda n=sts_name: e2e._get(STATEFULSET, n, ns), sts_name
            )
            assert deep_get(sts, "spec", "replicas") == 2, sts_name

        # The UI Service pins slice-0 worker-0 — the pod the kernels API
        # lives on — for multi-host AND multislice notebooks.
        svc = e2e._wait(lambda: e2e._get(SERVICE, "msc-nb", ns), "service")
        assert deep_get(svc, "spec", "selector") == {
            "statefulset.kubernetes.io/pod-name": "msc-nb-0"
        }, deep_get(svc, "spec", "selector")

        # An idle culler pass probes THAT Service URL (slice-0 worker-0 by
        # construction above) and stamps the stop annotation.
        probed = []
        culler = CullingReconciler(
            e2e.api_client, idle_minutes=0,
            prober=lambda url: probed.append(url) or [
                {"execution_state": "idle",
                 "last_activity": "2020-01-01T00:00:00Z"}],
        )
        culler.reconcile(Request(ns, "msc-nb"))
        assert probed == [
            f"http://msc-nb.{ns}.svc.cluster.local"
            f"/notebook/{ns}/msc-nb/api/kernels"
        ], probed
        nb = e2e.kube.get(NOTEBOOK, "msc-nb", ns)
        assert deep_get(nb, "metadata", "annotations",
                        "kubeflow-resource-stopped"), "stop not stamped"

        # Culling scales EVERY slice to zero...
        for sts_name in slice_stses:
            e2e._wait(
                lambda n=sts_name: deep_get(
                    e2e._get(STATEFULSET, n, ns), "spec", "replicas") == 0,
                f"{sts_name} scaled to 0",
            )
        # ...and restart restores every slice.
        resp = e2e.jupyter.patch(
            f"/api/namespaces/{ns}/notebooks/msc-nb",
            json={"stopped": False}, headers=e2e.user,
        )
        assert resp.status_code == 200, resp.get_data(as_text=True)
        for sts_name in slice_stses:
            e2e._wait(
                lambda n=sts_name: deep_get(
                    e2e._get(STATEFULSET, n, ns), "spec", "replicas") == 2,
                f"{sts_name} restored",
            )
    finally:
        e2e.close()


@check("webhook-merge-semantics")
def webhook_merge():
    """PodDefault merge: identical-or-error on name collisions, conflict
    rejected, provenance annotation stamped (reference main.go:97-148)."""
    from kubeflow_tpu.platform.webhook.mutate import (
        MergeConflict,
        apply_pod_defaults,
        safe_to_apply,
    )

    pod = {"metadata": {"labels": {"tpu": "true"}},
           "spec": {"containers": [{"name": "nb", "env": [
               {"name": "A", "value": "1"}]}]}}
    pd = {"metadata": {"name": "tpu-env", "resourceVersion": "5"},
          "spec": {"selector": {"matchLabels": {"tpu": "true"}},
                   "env": [{"name": "TPU_TOPOLOGY", "value": "2x4"}]}}
    out = apply_pod_defaults(pod, [pd])
    env = {e["name"]: e["value"] for e in out["spec"]["containers"][0]["env"]}
    assert env == {"A": "1", "TPU_TOPOLOGY": "2x4"}
    anns = out["metadata"]["annotations"]
    assert any("poddefault-tpu-env" in k for k in anns), anns

    conflict = {"metadata": {"name": "other", "resourceVersion": "6"},
                "spec": {"selector": {"matchLabels": {"tpu": "true"}},
                         "env": [{"name": "A", "value": "2"}]}}
    assert safe_to_apply(pod, [conflict]) is not None
    try:
        apply_pod_defaults(pod, [conflict])
    except MergeConflict:
        pass
    else:
        raise AssertionError("conflicting env merged silently")


@check("profile-workspace-rbac-quota")
def profile_rbac_quota():
    """A Profile materializes namespace + RBAC + TPU chip quota."""
    from kubeflow_tpu.platform.controllers.profile import ProfileReconciler
    from kubeflow_tpu.platform.k8s.types import (
        NAMESPACE, RESOURCEQUOTA, ROLEBINDING, SERVICEACCOUNT, deep_get,
    )
    from kubeflow_tpu.platform.runtime import Request
    from kubeflow_tpu.platform.testing import FakeKube

    kube = FakeKube()
    kube.add_namespace("default")
    kube.create({
        "apiVersion": "kubeflow.org/v1", "kind": "Profile",
        "metadata": {"name": "conf-user"},
        "spec": {"owner": {"kind": "User", "name": "conf@x.org"},
                 "resourceQuotaSpec": {"hard": {"google.com/tpu": "32"}}},
    })
    ProfileReconciler(kube).reconcile(Request("", "conf-user"))
    kube.get(NAMESPACE, "conf-user")
    kube.get(SERVICEACCOUNT, "default-editor", "conf-user")
    kube.get(ROLEBINDING, "namespaceAdmin", "conf-user")
    rq = kube.get(RESOURCEQUOTA, "kf-resource-quota", "conf-user")
    assert deep_get(rq, "spec", "hard", "google.com/tpu") == "32"


@check("tpu-quota-enforced")
def tpu_quota_enforced():
    """Per-namespace TPU chip quotas actually deny: an over-quota multi-host
    spawn is rejected with a user-facing 403, and succeeds once capacity is
    freed — the full Profile → ResourceQuota → admission chain the reference
    delegates to kube-apiserver (profile_controller.go:253-280 + KinD CI)."""
    from kubeflow_tpu.platform.k8s.types import (
        PROFILE, RESOURCEQUOTA, STATEFULSET, deep_get,
    )

    e2e = _e2e()
    try:
        e2e.kube.add_tpu_node("tpu-quota-1", topology="4x4")
        ns = e2e.register()
        # Admin caps the workspace at 16 chips through the Profile — the
        # platform's quota API — and the profile controller materializes it.
        profile = e2e.api_client.get(PROFILE, ns)
        profile["spec"]["resourceQuotaSpec"] = {
            "hard": {"google.com/tpu": "16"}}
        e2e.api_client.update(profile)
        rq = e2e._wait(
            lambda: e2e._get(RESOURCEQUOTA, "kf-resource-quota", ns), "quota"
        )
        assert deep_get(rq, "spec", "hard", "google.com/tpu") == "16"

        # An 8-chip notebook comes up and holds its chips.
        e2e.spawn(ns, "small-nb")
        # A 16-chip multi-host spawn now exceeds the 16-chip cap (8 used).
        resp = e2e.jupyter.post(
            f"/api/namespaces/{ns}/notebooks",
            json={"name": "big-nb",
                  "tpus": {"accelerator": "v5e", "topology": "4x4"}},
            headers=e2e.user,
        )
        body = resp.get_data(as_text=True)
        assert resp.status_code == 403, (resp.status_code, body)
        assert "TPU quota exceeded" in body, body
        assert "requested 16" in body and "remaining 8" in body, body

        # Free the capacity (delete the notebook AND its pods, as the
        # cluster would) — the same spawn must now succeed and go Ready.
        e2e.delete(ns, "small-nb")
        e2e._delete_pods(ns, "small-nb")
        resp = e2e.jupyter.post(
            f"/api/namespaces/{ns}/notebooks",
            json={"name": "big-nb",
                  "tpus": {"accelerator": "v5e", "topology": "4x4"}},
            headers=e2e.user,
        )
        assert resp.status_code == 200, resp.get_data(as_text=True)
        sts = e2e._wait(lambda: e2e._get(STATEFULSET, "big-nb", ns), "sts")
        assert deep_get(sts, "spec", "replicas") == 2
        e2e._kubelet_sim(ns, "big-nb", 2)  # pod admission passes at 16/16
        e2e._wait(lambda: e2e._phase(ns, "big-nb") == "running", "ready")
        used = deep_get(
            e2e.kube.get(RESOURCEQUOTA, "kf-resource-quota", ns),
            "status", "used", "google.com/tpu")
        assert used == "16", used
    finally:
        e2e.close()


@check("crd-version-conversion")
def crd_conversion():
    """Notebooks round-trip across every served version pair losslessly
    enough to preserve the TPU request."""
    from kubeflow_tpu.platform.apis import notebook as nbapi

    nb = {
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "c", "namespace": "x"},
        "spec": {"tpu": {"accelerator": "v5e", "topology": "2x4"},
                 "template": {"spec": {"containers": [{"name": "c"}]}}},
    }
    for version in nbapi.VERSIONS:
        there = nbapi.convert(nb, version)
        back = nbapi.convert(there, "v1beta1")
        assert back["spec"].get("tpu", {}).get("topology") == "2x4", (
            version, back)


@check("culling-idle-stop")
def culling_idle():
    """All-idle kernels past the window set the stop annotation; the
    reconciler then scales the slice to zero."""
    import datetime

    from kubeflow_tpu.platform.apis import notebook as nbapi
    from kubeflow_tpu.platform.controllers.culling import CullingReconciler
    from kubeflow_tpu.platform.k8s.types import NOTEBOOK
    from kubeflow_tpu.platform.runtime import Request
    from kubeflow_tpu.platform.testing import FakeKube

    kube = FakeKube()
    kube.add_namespace("u")
    kube.create({
        "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
        "metadata": {"name": "idle-nb", "namespace": "u"},
        "spec": {"template": {"spec": {"containers": [{"name": "idle-nb"}]}}},
    })
    now = datetime.datetime(2026, 1, 1, 12, 0,
                            tzinfo=datetime.timezone.utc)
    r = CullingReconciler(
        kube,
        prober=lambda url: [{"execution_state": "idle",
                             "last_activity": "2026-01-01T10:00:00Z"}],
        idle_minutes=60, now=lambda: now,
    )
    r.reconcile(Request("u", "idle-nb"))
    nb = kube.get(NOTEBOOK, "idle-nb", "u")
    assert nbapi.is_stopped(nb), "idle notebook was not stopped"


@check("tpujob-train-converge")
def tpujob_train_converge():
    """The two halves welded (ROADMAP item 4): a multislice TPUJob gang
    submitted through the in-memory API server trains the REAL ``train/``
    loop on CPU, loses a worker mid-run, and must gang-restart, resume
    from the checkpoint, and reach Succeeded with the loss decreased."""
    import dataclasses
    import shutil
    import tempfile
    import threading
    import time as _time

    from kubeflow_tpu.platform.apis import tpujob as jobapi
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.k8s.types import TPUJOB, deep_get
    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.testing.jobsim import TpuJobGangSim

    kube = FakeKube()
    kube.add_namespace("train")
    # 4 hosts of 4x4 (2 hosts/slice) = 2 slice slots: the 2-slice gang
    # fits whole under the capacity-gated admission queue.
    for i in range(4):
        kube.add_tpu_node(f"tpu-train-{i + 1}", topology="4x4")
    ckpt = tempfile.mkdtemp(prefix="tpujob-ckpt-")
    histories = []
    mid_run = threading.Event()

    def train_gang(job_name, generation, stop):
        # The gang's collective SPMD step, stood in by one CPU process:
        # tiny llama through the real train_loop + CheckpointManager, with
        # the controller-injected checkpoint dir and the graceful-stop
        # hook a preempted worker gets (train/run.py's SIGTERM handler).
        import jax
        import jax.numpy as jnp
        import optax

        from kubeflow_tpu.models.llama import CONFIGS, Llama
        from kubeflow_tpu.train import create_train_state, make_lm_train_step
        from kubeflow_tpu.train.loop import LoopConfig, train_loop

        cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=32)
        model = Llama(cfg)
        tokens = jnp.ones((4, 32), jnp.int32)
        state = create_train_state(
            jax.random.key(generation), model, tokens, optax.adamw(1e-3))
        step_fn = jax.jit(make_lm_train_step())

        def batches(start=0):
            def gen():
                i = start
                while True:
                    yield jax.random.randint(
                        jax.random.fold_in(jax.random.key(7), i),
                        (4, 32), 0, cfg.vocab_size)
                    i += 1
            return gen()

        def on_log(s, vals):
            # Generation 0 parks mid-run after step 8 and WAITS for the
            # preemption (the worker kill below) — deterministic: the
            # first generation can never outrun the chaos and finish.
            if generation == 0 and s >= 8:
                mid_run.set()
                stop.wait(60)

        _, history = train_loop(
            state, step_fn, batches,
            LoopConfig(total_steps=24, log_every=4,
                       checkpoint_dir=ckpt, checkpoint_every=4),
            on_log=on_log,
            stop=stop,
        )
        histories.append(history)

    sim = TpuJobGangSim(kube, "train", work=train_gang)
    ctrl = jobctrl.make_controller(kube)
    ctrl.start(kube)

    def wait(fn, what, timeout=120.0):
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if fn():
                return
            _time.sleep(0.05)
        raise TimeoutError(f"tpujob conformance: timed out on {what}")

    def job():
        return kube.get(TPUJOB, "llama-train", "train")

    try:
        # 4x4 on v5e = 16 chips / 2 hosts per slice; 2 slices over DCN.
        t_submit = _time.time()
        kube.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": "llama-train", "namespace": "train"},
            "spec": {
                "tpu": {"accelerator": "v5e", "topology": "4x4",
                        "slices": 2},
                "template": {"spec": {"containers": [{
                    "name": "worker",
                    "image": "ghcr.io/kubeflow-tpu/trainer",
                    "command": ["python", "-m", "kubeflow_tpu.train.run"],
                }]}},
                "restartPolicy": "OnFailure",
                "backoffLimit": 2,
                "checkpointDir": ckpt,
            },
        })
        # Tight poll: t_running feeds the journey-vs-wall assertion
        # below, and the default 50 ms cadence would eat the tolerance.
        deadline = _time.monotonic() + 120.0
        while jobapi.phase_of(job()) != "Running":
            if _time.monotonic() >= deadline:
                raise TimeoutError("tpujob conformance: gang Running")
            _time.sleep(0.01)
        t_running = _time.time()
        wait(mid_run.is_set, "first generation mid-run")
        # Preempt slice 1's worker 0: the gang must tear down WHOLE.
        kube.set_pod_phase("train", "llama-train-s1-0", "Failed")
        wait(lambda: jobapi.restarts_of(job()) == 1, "gang restart")
        wait(lambda: jobapi.phase_of(job()) == "Succeeded",
             "checkpoint-resume to Succeeded", timeout=180.0)
    finally:
        ctrl.stop()
        sim.close()
        shutil.rmtree(ckpt, ignore_errors=True)

    assert not sim.errors, sim.errors
    final = job()
    assert jobapi.restarts_of(final) == 1, final.get("status")
    for s in deep_get(final, "status", "slices", default=[]):
        assert s["total"] == 2, final.get("status")

    # -- the merged causal journey (ISSUE 14 acceptance) ----------------
    # One trace_id links submit → admission → gang create → pod start →
    # Running, and survives the gang restart: the generation-1 StatefulSet
    # creates land on the SAME journey as generation 0's.
    from kubeflow_tpu.telemetry import causal, critical_path

    jctx = causal.from_object(final)
    assert jctx is not None, "TPUJob lost its traceparent annotation"
    spans = causal.merge_journeys(causal.journey(jctx.trace_id))
    assert spans, "journey is empty"
    # Trace continuity across the gang restart: 2 slices x 2 generations
    # of StatefulSet creates on one trace_id.
    sts_creates = [s for s in spans
                   if s.get("segment") == "write_rtt"
                   and s.get("kind") == "StatefulSet"
                   and s["name"] == "k8s.create"]
    assert len(sts_creates) >= 4, (
        f"gang restart severed the journey: only {len(sts_creates)} "
        f"StatefulSet creates on trace {jctx.trace_id}")
    # Submit→Running critical path: clip the journey to the Running
    # observation, decompose, and check (a) exactly one admission_queue
    # segment and (b) the named segments sum to the measured wall time
    # within 10% (floor 0.12 s — the Running poll granularity plus
    # 2-CPU-container scheduling noise must not flake the band).
    clipped = [s for s in spans if s["end_ts"] <= t_running + 0.02]
    d = critical_path.decompose(clipped)
    admission = [e for e in d["path"]
                 if e.get("segment") == "admission_queue"]
    assert len(admission) == 1, (
        f"submit→Running critical path carries {len(admission)} "
        f"admission_queue segments: {[e['name'] for e in d['path']]}")
    wall = t_running - t_submit
    total = sum(d["segments"].values())
    assert abs(total - wall) <= max(0.10 * wall, 0.12), (
        f"critical-path segments sum to {total:.3f}s vs measured "
        f"submit→Running wall {wall:.3f}s "
        f"(segments: {d['segments']})")

    assert len(histories) == 2, [len(h) for h in histories]
    first_gen, resumed = histories
    # Resume really happened: the second generation's first logged step is
    # past the first generation's start — not a from-scratch rerun.
    assert resumed[0]["step"] > first_gen[0]["step"], (
        first_gen[0], resumed[0])
    assert resumed[-1]["step"] == 24, resumed[-1]
    assert resumed[-1]["loss"] < first_gen[0]["loss"], (
        first_gen[0]["loss"], resumed[-1]["loss"])


@check("tpujob-queue-preempt-elastic")
def tpujob_queue_preempt_elastic():
    """ISSUE 11 acceptance: three profiles submit six TPUJobs into a
    4-slice budget under a seeded ChaosKube storm.  The queue must drain
    in priority-then-FIFO order; one high-priority job preempts the
    low-priority gang, which checkpoint-saves through the REAL train
    loop, resumes elastically at minSlices, and grows back to its full
    slices after the preemptor finishes — never a half-admitted gang,
    zero lost jobs, zero duplicate gangs, zero dead-letters.  (The
    replica-kill half of the invariant set is pinned by
    tests/ctrlplane/test_jobqueue.py::
    test_sharded_replica_kill_preserves_drain_order.)"""
    import dataclasses
    import shutil
    import tempfile
    import threading
    import time as _time

    from kubeflow_tpu.platform.apis import tpujob as jobapi
    from kubeflow_tpu.platform.controllers import tpujob as jobctrl
    from kubeflow_tpu.platform.k8s.types import STATEFULSET, TPUJOB, deep_get
    from kubeflow_tpu.platform.runtime.controller import make_workqueue
    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.testing.chaos import ChaosKube, storm
    from kubeflow_tpu.platform.testing.jobsim import TpuJobGangSim

    kube = FakeKube()
    for ns in ("team-a", "team-b", "team-c"):
        kube.add_namespace(ns)
        kube.create({
            "apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": {"name": "kf-resource-quota", "namespace": ns},
            "spec": {"hard": {"google.com/tpu": "32"}},
        })
    # The 4-slice budget: 4 single-host v5e 2x4 nodes.
    for i in range(4):
        kube.add_tpu_node(f"tpu-q-{i + 1}", topology="2x4")
    ckpt = tempfile.mkdtemp(prefix="tpujob-elastic-ckpt-")
    histories = []
    parked = {0: threading.Event(), 1: threading.Event()}
    done = {name: threading.Event()
            for name in ("mid", "q1", "q2", "q3", "high")}

    def train_low(job_name, generation, stop):
        # The preemption victim trains the REAL loop: generation 0 parks
        # mid-run awaiting the eviction, generation 1 (elastic, 1 slice)
        # parks awaiting the grow-back, generation 2 runs to completion.
        import jax
        import jax.numpy as jnp
        import optax

        from kubeflow_tpu.models.llama import CONFIGS, Llama
        from kubeflow_tpu.train import create_train_state, make_lm_train_step
        from kubeflow_tpu.train.loop import LoopConfig, train_loop

        cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=32)
        model = Llama(cfg)
        tokens = jnp.ones((4, 32), jnp.int32)
        state = create_train_state(
            jax.random.key(generation), model, tokens, optax.adamw(1e-3))
        step_fn = jax.jit(make_lm_train_step())

        def batches(start=0):
            def gen():
                i = start
                while True:
                    yield jax.random.randint(
                        jax.random.fold_in(jax.random.key(7), i),
                        (4, 32), 0, cfg.vocab_size)
                    i += 1
            return gen()

        def on_log(s, vals):
            if generation in parked and (generation > 0 or s >= 8):
                parked[generation].set()
                stop.wait(60)

        _, history = train_loop(
            state, step_fn, batches,
            LoopConfig(total_steps=24, log_every=4,
                       checkpoint_dir=ckpt, checkpoint_every=4),
            on_log=on_log, stop=stop)
        histories.append(history)

    def gated(name):
        def work(job_name, generation, stop):
            done[name].wait(120)
        return work

    def team_work(mapping):
        def work(job_name, generation, stop):
            return mapping[job_name](job_name, generation, stop)
        return work

    sims = [
        TpuJobGangSim(kube, "team-a", work=team_work(
            {"low": train_low, "q1": gated("q1")})),
        TpuJobGangSim(kube, "team-b", work=team_work(
            {"mid": gated("mid"), "q2": gated("q2")})),
        TpuJobGangSim(kube, "team-c", work=team_work(
            {"q3": gated("q3"), "high": gated("high")})),
    ]
    # Seeded storm on the controller's entire apiserver path; the sims
    # keep talking to the healthy store (only the control plane flakes).
    chaos = ChaosKube(kube, storm(rate=0.03, max_injections=60),
                      seed=20260811)
    ctrl = jobctrl.make_controller(chaos, preemption_grace=1.0,
                                   queue_poll=0.2)
    ctrl.queue = make_workqueue(base_delay=0.05, max_delay=2.0)

    admissions = []       # (name, generation) on first sight admitted
    sts_events = []       # (etype, name, generation-label)
    stop_watch = threading.Event()

    def job_watch():
        seen = set()
        for _etype, job in kube.watch(TPUJOB, None, stop=stop_watch):
            if jobapi.allocated_slices(job) is not None:
                key = (job["metadata"]["name"], jobapi.generation_of(job))
                if key not in seen:
                    seen.add(key)
                    admissions.append(key)

    def sts_watch():
        for etype, sts in kube.watch(STATEFULSET, None, stop=stop_watch):
            labels = deep_get(sts, "metadata", "labels", default={}) or {}
            sts_events.append((etype, sts["metadata"]["name"],
                               labels.get(jobapi.LABEL_GENERATION)))

    for fn in (job_watch, sts_watch):
        threading.Thread(target=fn, daemon=True).start()
    ctrl.start(chaos)

    def wait(fn, what, timeout=90.0):
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if fn():
                return
            _time.sleep(0.05)
        raise TimeoutError(f"tpujob-queue conformance: timed out on {what}")

    def job(name, ns):
        return kube.get(TPUJOB, name, ns)

    def submit(name, ns, *, priority, slices, min_slices=None, ckpt_dir=None):
        spec = {
            "tpu": {"accelerator": "v5e", "topology": "2x4",
                    "slices": slices},
            "template": {"spec": {"containers": [{
                "name": "worker", "image": "trainer",
                "command": ["python", "-m", "kubeflow_tpu.train.run"],
            }]}},
            "priority": priority,
        }
        if min_slices is not None:
            spec["tpu"]["minSlices"] = min_slices
        if ckpt_dir is not None:
            spec["checkpointDir"] = ckpt_dir
        kube.create({
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": ns}, "spec": spec,
        })

    try:
        # Phase 1 — fill the budget: low (3 slices, elastic to 1) + mid.
        # Priorities: only high (500) outranks low (150) — the parked
        # jobs (100-120) must WAIT behind the running fleet, not preempt
        # it themselves.
        submit("low", "team-a", priority=150, slices=3, min_slices=1,
               ckpt_dir=ckpt)
        wait(lambda: jobapi.phase_of(job("low", "team-a")) == "Running",
             "low Running")
        wait(parked[0].is_set, "low mid-run")
        submit("mid", "team-b", priority=300, slices=1)
        wait(lambda: jobapi.phase_of(job("mid", "team-b")) == "Running",
             "mid Running")
        # Phase 2 — the queue forms: q3 outranks q1/q2; FIFO inside 100.
        submit("q3", "team-c", priority=120, slices=1)
        submit("q1", "team-a", priority=100, slices=1)
        submit("q2", "team-b", priority=100, slices=1)
        for name, ns in (("q3", "team-c"), ("q1", "team-a"),
                         ("q2", "team-b")):
            wait(lambda n=name, s=ns:
                 jobapi.phase_of(job(n, s)) == "Queued",
                 f"{name} Queued")
        # Phase 3 — the preemptor: high (500) needs 3 slices.  Victim
        # selection is lowest-priority-first and MINIMAL: low (150)
        # alone frees 3 slices, so mid (300) is never touched.
        submit("high", "team-c", priority=500, slices=3)
        wait(lambda: jobapi.phase_of(job("low", "team-a")) == "Queued",
             "low preempted after checkpoint")
        wait(lambda: (jobapi.phase_of(job("high", "team-c")) == "Running"
                      and jobapi.allocated_slices(
                          job("high", "team-c")) == 3),
             "high admitted whole")
        assert jobapi.phase_of(job("mid", "team-b")) == "Running"
        # Phase 4 — elastic resume: mid finishes, freeing ONE slice; the
        # re-queued low (150) is the head and re-admits at minSlices=1,
        # its REAL train loop restoring the checkpoint.
        done["mid"].set()
        wait(lambda: (jobapi.allocated_slices(job("low", "team-a")) == 1
                      and jobapi.phase_of(
                          job("low", "team-a")) == "Running"),
             "low resumed elastically at 1 slice")
        wait(parked[1].is_set, "low gen-1 mid-run")
        sts = kube.get(STATEFULSET, "low", "team-a")
        env = {e["name"]: e.get("value") for e in deep_get(
            sts, "spec", "template", "spec", "containers")[0]["env"]}
        assert env["MEGASCALE_NUM_SLICES"] == "1", env
        assert env["KFT_SPEC_SLICES"] == "3", env
        # Phase 5 — the preemptor finishes; the rest of the queue drains
        # in rank order (q3 before the FIFO pair q1, q2).
        done["high"].set()
        wait(lambda: jobapi.phase_of(job("q3", "team-c")) == "Running",
             "q3 admitted")
        done["q3"].set()
        wait(lambda: jobapi.phase_of(job("q1", "team-a")) == "Running",
             "q1 admitted")
        done["q1"].set()
        wait(lambda: jobapi.phase_of(job("q2", "team-b")) == "Running",
             "q2 admitted")
        done["q2"].set()
        # Phase 6 — with the queue empty, low grows back to its full 3
        # slices via a graceful checkpoint-restart and completes.
        wait(lambda: jobapi.allocated_slices(job("low", "team-a")) == 3,
             "low grown back to 3 slices", timeout=120.0)
        wait(lambda: jobapi.phase_of(job("low", "team-a")) == "Succeeded",
             "low Succeeded", timeout=180.0)
    finally:
        stop_watch.set()
        ctrl.stop()
        for sim in sims:
            sim.close()
        shutil.rmtree(ckpt, ignore_errors=True)

    for sim in sims:
        assert not sim.errors, sim.errors
    # Drain order: priority-then-FIFO across the three profiles — the
    # re-queued low (150) resumes ahead of the 100-120 band, the band
    # drains q3-first then FIFO, and low's grow-back is the final gang.
    assert admissions == [
        ("low", 0), ("mid", 0), ("high", 0), ("low", 1), ("q3", 0),
        ("q1", 0), ("q2", 0), ("low", 2),
    ], admissions
    # Never half-admitted: high's first StatefulSet appears only AFTER
    # every generation-0 low StatefulSet was torn down (the checkpoint
    # eviction completed first).
    high_first = min(i for i, (e, n, _g) in enumerate(sts_events)
                     if n.startswith("high") and e == "ADDED")
    low_gen0_deletes = [i for i, (e, n, g) in enumerate(sts_events)
                        if n.startswith("low") and e == "DELETED"
                        and g == "0"]
    assert len(low_gen0_deletes) >= 3, sts_events
    assert sorted(low_gen0_deletes)[2] < high_first, (
        low_gen0_deletes, high_first)
    # The victim really resumed: three generations of the real train
    # loop, monotonically advancing steps, loss improved end to end.
    assert len(histories) == 3, [len(h) for h in histories]
    gen0, gen1, gen2 = histories
    assert gen1[0]["step"] > gen0[0]["step"], (gen0[0], gen1[0])
    assert gen2[-1]["step"] == 24, gen2[-1]
    assert gen2[-1]["loss"] < gen0[0]["loss"], (gen0[0], gen2[-1])
    final = job("low", "team-a")
    assert jobapi.restarts_of(final) == 0, final.get("status")  # no failures
    assert jobapi.generation_of(final) == 2, final.get("status")
    # Zero lost jobs / duplicate gangs / dead-letters under the storm.
    assert not ctrl.dead_letters
    for ns in ("team-a", "team-b", "team-c"):
        for j in kube.list(TPUJOB, ns):
            assert jobapi.phase_of(j) == "Succeeded", (
                j["metadata"]["name"], j.get("status"))
    assert chaos.injected() > 0, "the storm never stormed"


@check("inferenceservice-autoscale-rollout")
def inferenceservice_autoscale_rollout():
    """ISSUE 12 acceptance: an InferenceService serving a REAL llama_debug
    model server scales 2→N under synthetic client load (the serve series
    scraped over real HTTP from the replicas' live /metrics pages), rolls
    a new checkpoint revision — written through train/checkpoint.py,
    warmed by the real /readyz one-token generate(), traffic flipped only
    after it passes — with ZERO dropped requests, scales to zero when the
    traffic stops, and wakes on the next request via the activator
    annotation.  All of it under a seeded ChaosKube storm on BOTH
    controller replicas of a ShardedFleet, with one replica KILLED
    mid-wave and the fencing invariant held across the handover."""
    import dataclasses
    import json as _json
    import shutil
    import tempfile
    import threading
    import time as _time
    import urllib.request

    import jax
    import jax.numpy as jnp
    from werkzeug.serving import make_server

    from kubeflow_tpu.platform.apis import inferenceservice as svcapi
    from kubeflow_tpu.platform.controllers import (
        inferenceservice as svcctrl,
    )
    from kubeflow_tpu.platform.k8s.types import (
        INFERENCESERVICE,
        SERVICE,
        deep_get,
    )
    from kubeflow_tpu.platform.testing.chaos import storm
    from kubeflow_tpu.platform.testing.servesim import InferenceFleetSim
    from kubeflow_tpu.platform.testing.shardfleet import ShardedFleet

    # The lock-serialized serve path keeps the scenario CPU-budget-friendly
    # (no pool-decode compile per revision); queue depth and TTFT are the
    # scraped series either way.
    os.environ["KFT_SERVE_SCHEDULER"] = "0"

    # -- real model backends, one per revision ----------------------------
    from kubeflow_tpu.models.llama import CONFIGS, Llama
    from kubeflow_tpu.models.serve import create_app, load_service

    servers = []          # (server, thread) for teardown
    backends = {}         # revision str -> base url

    def start_backend(revision: int, service_obj):
        app = create_app(service_obj, model_name="llama_debug",
                         revision=revision)
        server = make_server("127.0.0.1", 0, app, threaded=True)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        servers.append((server, t))
        backends[str(revision)] = f"http://127.0.0.1:{server.server_port}"

    start_backend(1, load_service("llama_debug", max_seq_len=64))

    # -- the cluster: 2 sharded controller replicas under a seeded storm --
    # One private EndpointBook shared by both replicas' reconcilers and
    # the phase-6 activator: the controller PUBLISHES ready endpoints,
    # the front door reads them — the production seam, hermetically.
    from kubeflow_tpu.platform.activator import EndpointBook

    book = EndpointBook()
    fleet = ShardedFleet(
        replicas=2, num_shards=4, namespace="serve",
        chaos_faults=storm(rate=0.03, max_injections=60),
        chaos_seed=20260812,
        controller_factory=lambda client, **kw: svcctrl.make_controller(
            client, sync_period=0.25, book=book, **kw),
    )
    kube = fleet.kube
    kube.create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota", "namespace": "serve"},
        "spec": {"hard": {"google.com/tpu": "64"}},
    })

    def http_ok(url, timeout=120.0):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return resp.status == 200
        except Exception:
            return False

    # The kubelet half: pods come up per Deployment, Ready gated on the
    # REAL server's /readyz (the warm one-token generate actually runs).
    sim = InferenceFleetSim(
        kube, "serve",
        endpoint_for=lambda svc, rev, i: backends.get(rev),
        ready_gate=lambda svc, rev, i: (rev in backends
                                        and http_ok(backends[rev]
                                                    + "/readyz")),
    )

    # -- traffic: every request must succeed, flip or storm or not --------
    stop_traffic = threading.Event()
    failures = []
    served = {"count": 0}

    def resolved_backend():
        try:
            service = kube.get(SERVICE, "llm", "serve")
        except Exception:
            return None
        rev = deep_get(service, "spec", "selector",
                       svcapi.LABEL_REVISION)
        return backends.get(rev)

    def traffic_loop():
        body = _json.dumps({"tokens": [[5, 9, 2, 7]],
                            "max_new_tokens": 4}).encode()
        while not stop_traffic.is_set():
            base = resolved_backend()
            if base is None:
                failures.append("no backend resolvable")
                break
            try:
                req = urllib.request.Request(
                    base + "/v1/generate", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    out = _json.loads(resp.read())
                    assert len(out["tokens"][0]) == 4
                    served["count"] += 1
            except Exception as e:  # noqa: BLE001 — the count IS the check
                failures.append(f"{type(e).__name__}: {e}")
            _time.sleep(0.03)

    def wait(fn, what, timeout=120.0):
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if fn():
                return
            _time.sleep(0.05)
        status = {}
        try:
            status = kube.get(INFERENCESERVICE, "llm",
                              "serve").get("status") or {}
        except Exception:
            pass
        raise TimeoutError(
            f"inferenceservice conformance: timed out on {what} "
            f"(status {status}, failures {failures[:3]})")

    def status():
        return kube.get(INFERENCESERVICE, "llm", "serve").get(
            "status") or {}

    ckpt = tempfile.mkdtemp(prefix="isvc-ckpt-")
    traffic_threads = []
    try:
        kube.create({
            "apiVersion": "kubeflow.org/v1alpha1",
            "kind": "InferenceService",
            "metadata": {"name": "llm", "namespace": "serve"},
            "spec": {
                "model": "llama_debug",
                "maxSeqLen": 64,
                "tpu": {"accelerator": "v5e", "topology": "2x4"},
                "replicas": {"min": 0, "max": 4, "initial": 2},
                "scale": {
                    # Any real CPU TTFT (≥ ~1 ms) is far above this
                    # ceiling, so sustained traffic deterministically
                    # drives the width to its max.
                    "ttftP99TargetSeconds": 0.0005,
                    "queueDepthTarget": 4.0,
                    # Long while traffic flows; phase 5 shortens it by a
                    # spec patch (scale knobs never roll a revision).
                    "idleSeconds": 300.0,
                    "cooldownSeconds": 0.2,
                },
            },
        })
        # Phase 1 — the initial pool warms through the real /readyz
        # (2 replicas requested; traffic starts the moment the first is
        # Ready, before the no-load autoscaler can draw the pool down).
        wait(lambda: status().get("phase") == "Ready"
             and status().get("readyReplicas", 0) >= 1,
             "initial replicas Ready")

        # Phase 2 — the wave: real clients; TTFT scraped over real HTTP
        # scales the service to its 4-replica ceiling.
        for _ in range(2):
            t = threading.Thread(target=traffic_loop, daemon=True)
            t.start()
            traffic_threads.append(t)
        wait(lambda: status().get("replicas") == 4
             and status().get("readyReplicas") == 4,
             "traffic wave scale-up 2->4")

        # Phase 3 — kill controller replica 0 MID-WAVE: the survivor
        # absorbs the shards; scaling and the coming rollout continue.
        kill_t = _time.monotonic()
        fleet.kill(0)

        # Phase 4 — rolling weight update: a REAL checkpoint written
        # through train/checkpoint.py becomes revision 2; it warms, the
        # real readiness generate() passes, traffic flips, revision 1
        # drains — with the clients still hammering and zero failures.
        import optax

        from kubeflow_tpu.train import create_train_state
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        cfg = dataclasses.replace(CONFIGS["llama_debug"], max_seq_len=64)
        model = Llama(cfg)
        state = create_train_state(
            jax.random.key(7), model, jnp.ones((1, 8), jnp.int32),
            optax.sgd(1e-3))
        with CheckpointManager(ckpt, max_to_keep=1) as mgr:
            mgr.save(1, state, force=True)
        start_backend(2, load_service("llama_debug", max_seq_len=64,
                                      checkpoint_dir=ckpt))
        svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
        svc["spec"] = dict(svc["spec"], checkpointDir=ckpt)
        kube.update(svc)
        wait(lambda: status().get("revision") == 2
             and status().get("readyReplicas", 0) >= 1,
             "rolling update flips to revision 2")
        # The Service now routes to replicas that really serve the new
        # revision (their own /metrics says so).
        page = urllib.request.urlopen(
            resolved_backend() + "/metrics", timeout=10).read().decode()
        assert "serve_replica_revision 2.0" in page, page[-500:]

        # Phase 5 — traffic stops: the width drains, and with the idle
        # window shortened (an operator knob edit, NOT a revision — the
        # pods never restart) the service scales to ZERO.
        stop_traffic.set()
        for t in traffic_threads:
            t.join(timeout=70)
        svc = dict(kube.get(INFERENCESERVICE, "llm", "serve"))
        svc["spec"] = dict(svc["spec"], scale={
            **svc["spec"]["scale"], "idleSeconds": 1.5})
        kube.update(svc)
        wait(lambda: status().get("replicas") == 0
             and status().get("phase") == "Idle",
             "idle scale-to-zero")
        assert status().get("revision") == 2  # the knob edit rolled nothing

        # Phase 6 — the next requests wake it THROUGH the front door
        # (ISSUE 19): a LIVE activator on the wire holds them across the
        # cold start, stamps the wake-at annotation itself (no harness
        # stamping), and replays once the pool passes the real /readyz
        # warm generate.  Zero dropped requests, asserted from the wire:
        # eight concurrent clients hit the scaled-to-zero service and
        # every one of them gets a 200 with real tokens back.
        from kubeflow_tpu.models.client import GenerateClient
        from kubeflow_tpu.platform.activator import (
            Activator,
            create_activator_app,
        )

        os.environ["KFT_ACTIVATOR_RESTAMP_SECONDS"] = "0.2"
        activator = Activator(kube, book=book)
        act_server = make_server("127.0.0.1", 0,
                                 create_activator_app(activator),
                                 threaded=True)
        act_thread = threading.Thread(target=act_server.serve_forever,
                                      daemon=True)
        act_thread.start()
        servers.append((act_server, act_thread))
        front = GenerateClient(
            f"http://127.0.0.1:{act_server.server_port}/serve/serve/llm",
            tenant="wake-client", timeout=120.0)
        wake_results = [None] * 8

        def wake_request(i):
            wake_results[i] = front.generate([[5, 9, 2, 7]],
                                             max_new_tokens=4)

        wake_threads = [threading.Thread(target=wake_request, args=(i,))
                        for i in range(8)]
        for t in wake_threads:
            t.start()
        # The ACTIVATOR stamps the wake annotation, not this harness.
        wait(lambda: svcapi.ANNOTATION_WAKE in (
            kube.get(INFERENCESERVICE, "llm", "serve")["metadata"]
            .get("annotations") or {}), "activator wake stamp")
        wait(lambda: status().get("phase") == "Ready"
             and status().get("readyReplicas", 0) >= 1,
             "cold-start wake to Ready")
        for t in wake_threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in wake_threads), (
            "wake replay hung")
        assert all(r is not None and r.ok for r in wake_results), [
            (r.status, r.log) for r in wake_results
            if r is None or not r.ok]
        assert all(len(r.tokens[0]) == 4 for r in wake_results)

        # The killed replica never wrote after its lease deadline, and
        # every write that reached the wire was fenced inside an
        # ownership window — across the kill.
        checked = fleet.assert_fencing_invariant(
            kinds={"InferenceService", "Deployment", "Service",
                   "VirtualService"})
        assert checked > 0, "no fenced writes checked"
        fleet.assert_no_writes_after(
            0, kill_t + fleet.lease_seconds + 0.5,
            kinds={"InferenceService", "Deployment", "Service",
                   "VirtualService"})
    finally:
        stop_traffic.set()
        fleet.close()
        sim.close()
        for server, t in servers:
            server.shutdown()
            t.join(timeout=5)
        shutil.rmtree(ckpt, ignore_errors=True)
        os.environ.pop("KFT_SERVE_SCHEDULER", None)
        os.environ.pop("KFT_ACTIVATOR_RESTAMP_SECONDS", None)

    # Zero dropped requests, real traffic actually flowed, the storm
    # actually stormed, the sim saw no errors.
    assert not failures, failures[:5]
    assert served["count"] > 20, served
    assert not sim.errors, sim.errors
    assert sum(r.chaos.injected() for r in fleet.replicas) > 0, (
        "the storm never stormed")


@check("inferenceservice-noisy-neighbor")
def inferenceservice_noisy_neighbor():
    """ISSUE 19 acceptance: per-tenant QoS at the front door, asserted
    from the wire.  Two tenants share one real llama_debug replica
    behind a live activator.  The hammering tenant blows through its
    token bucket and is shed with structured 429 + Retry-After; the
    quiet tenant sees zero non-200s and its client-observed TTFT p99
    stays within a generous bound — one tenant's storm never becomes
    another tenant's outage.  (The controller→EndpointBook discovery
    seam is pinned by tests/ctrlplane/test_activator.py; this check
    exercises the data path end to end over real HTTP.)"""
    import json as _json
    import threading
    import time as _time
    import urllib.request

    from werkzeug.serving import make_server

    from kubeflow_tpu.models.client import GenerateClient
    from kubeflow_tpu.models.serve import create_app, load_service
    from kubeflow_tpu.platform.activator import (
        Activator,
        EndpointBook,
        create_activator_app,
    )

    # Lock-serialized serve path (CPU budget) + a tight, deterministic
    # tenant budget: 5 req/s refill over a 10-token burst is far below
    # what the hammer sends and far above what the quiet tenant needs.
    os.environ["KFT_SERVE_SCHEDULER"] = "0"
    os.environ["KFT_ACTIVATOR_TENANT_RATE"] = "5"
    os.environ["KFT_ACTIVATOR_TENANT_BURST"] = "10"

    class _NoWake:
        """The service never goes cold here; a wake patch is a bug."""

        def patch(self, *a, **kw):
            raise AssertionError(
                "activator stamped wake-at for a warm service")

    servers = []
    try:
        svc = load_service("llama_debug", max_seq_len=64)
        backend = make_server("127.0.0.1", 0,
                              create_app(svc, model_name="llama_debug",
                                         revision=1), threaded=True)
        bt = threading.Thread(target=backend.serve_forever, daemon=True)
        bt.start()
        servers.append((backend, bt))
        base = f"http://127.0.0.1:{backend.server_port}"
        # Warm through the real /readyz so TTFT below is steady-state.
        with urllib.request.urlopen(base + "/readyz", timeout=120) as r:
            assert r.status == 200

        # A generous TTFT target keeps the SLO knee off: every shed in
        # this check is the tenant bucket, deterministically.
        book = EndpointBook()
        book.publish("serve/llm", endpoints=[base], ttft_target_s=30.0,
                     phase="Ready")
        activator = Activator(_NoWake(), book=book)
        act_server = make_server("127.0.0.1", 0,
                                 create_activator_app(activator),
                                 threaded=True)
        at = threading.Thread(target=act_server.serve_forever,
                              daemon=True)
        at.start()
        servers.append((act_server, at))
        front = (f"http://127.0.0.1:{act_server.server_port}"
                 "/serve/serve/llm")

        stop = threading.Event()
        hammer_results, quiet_results, quiet_ttft = [], [], []

        def hammer_loop():
            client = GenerateClient(front, tenant="hammer",
                                    priority="batch", timeout=60.0)
            while not stop.is_set():
                hammer_results.append(client.generate(
                    [[5, 9, 2, 7]], max_new_tokens=2))

        def quiet_loop():
            client = GenerateClient(front, tenant="quiet",
                                    priority="interactive", timeout=60.0)
            while not stop.is_set():
                t0 = _time.perf_counter()
                quiet_results.append(client.generate(
                    [[5, 9, 2, 7]], max_new_tokens=2))
                quiet_ttft.append(_time.perf_counter() - t0)
                _time.sleep(0.3)

        threads = [threading.Thread(target=hammer_loop, daemon=True)
                   for _ in range(2)]
        threads.append(threading.Thread(target=quiet_loop, daemon=True))
        for t in threads:
            t.start()
        _time.sleep(4.0)
        stop.set()
        for t in threads:
            t.join(timeout=70)

        # The hammering tenant was shed, structurally: wire 429s with a
        # Retry-After hint — and its admitted burst still served.
        sheds = [r for r in hammer_results if r.status == 429]
        assert sheds, "the hammer was never shed"
        assert all(r.retry_after is not None and r.retry_after >= 1
                   for r in sheds), sheds[:3]
        assert all("admission rate" in r.log for r in sheds), sheds[:3]
        assert any(r.ok for r in hammer_results), (
            "the hammer's admitted burst never served")
        bad = [r for r in hammer_results if r.status not in (200, 429)]
        assert not bad, [(r.status, r.log) for r in bad[:3]]

        # The quiet tenant never felt it: zero non-200s, TTFT p99 sane.
        assert quiet_results, "quiet tenant sent no traffic"
        not_ok = [r for r in quiet_results if not r.ok]
        assert not not_ok, [(r.status, r.log) for r in not_ok[:3]]
        p99 = sorted(quiet_ttft)[int(0.99 * (len(quiet_ttft) - 1))]
        assert p99 < 10.0, f"quiet tenant TTFT p99 {p99:.3f}s"
        # And the activator's own accounting agrees with the wire.
        from kubeflow_tpu.platform.runtime import metrics as _rm

        assert (_rm.registry.get_sample_value(
            "serve_requests_shed_total",
            {"tenant": "hammer", "reason": "tenant-bucket"}) or 0) \
            >= len(sheds)
        assert _json.dumps(activator.debug_snapshot())  # serializable
    finally:
        for server, t in servers:
            server.shutdown()
            t.join(timeout=5)
        os.environ.pop("KFT_SERVE_SCHEDULER", None)
        os.environ.pop("KFT_ACTIVATOR_TENANT_RATE", None)
        os.environ.pop("KFT_ACTIVATOR_TENANT_BURST", None)


@check("api-authn-authz")
def api_authn_authz():
    """Identity comes from the trusted header; requests without it are 401
    and SubjectAccessReview denials are 403 (reference authn.py/authz.py)."""
    from werkzeug.test import Client

    from kubeflow_tpu.platform.apps.jupyter.app import create_app
    from kubeflow_tpu.platform.testing import FakeKube

    kube = FakeKube()
    kube.add_namespace("u")
    app = create_app(kube, secure_cookies=False)
    c = Client(app)
    assert c.get("/api/config").status_code == 401
    kube.authz_policy = lambda **kw: False
    resp = c.get("/api/namespaces/u/notebooks",
                 headers={"kubeflow-userid": "eve@x.org"})
    assert resp.status_code == 403
    kube.authz_policy = None
    assert c.get("/api/namespaces/u/notebooks",
                 headers={"kubeflow-userid": "eve@x.org"}).status_code == 200


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--report", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "report.json"))
    ap.add_argument("--only", default="",
                    help="comma-separated subset of checks to run")
    args = ap.parse_args(argv)

    only = {n for n in args.only.split(",") if n}
    unknown = only - {n for n, _ in CHECKS}
    if unknown:
        print(f"unknown checks: {sorted(unknown)}", file=sys.stderr)
        return 2
    selected = [(n, f) for n, f in CHECKS if not only or n in only]

    # The always-on profiler runs over the whole suite, exactly as it
    # would in production (ISSUE 16): the report carries per-role sample
    # counts, so a conformance run doubles as a living demonstration
    # that the sampler attributes real control-plane work — and a slow
    # scenario leaves a flamegraph behind instead of a shrug.
    from kubeflow_tpu.telemetry import profiler as profiler_mod

    prof = profiler_mod.Profiler()
    prof.start()
    profiler_mod.register_debug_profiler(prof)

    results = []
    try:
        for name, fn in selected:
            t0 = time.perf_counter()
            try:
                fn()
                results.append({"check": name, "passed": True,
                                "seconds": round(time.perf_counter() - t0, 3)})
                print(f"PASS {name}")
            except Exception:
                results.append({
                    "check": name, "passed": False,
                    "seconds": round(time.perf_counter() - t0, 3),
                    "error": traceback.format_exc(limit=5),
                })
                print(f"FAIL {name}")
                traceback.print_exc(limit=5)
    finally:
        profiler_mod.register_debug_profiler(None)
        prof.stop()
    profile_roles = {}
    for win in [prof.folded(w["window"]) or "" for w in prof.windows()]:
        for line in win.splitlines():
            role = line.split(";", 1)[0]
            count = int(line.rsplit(" ", 1)[1])
            profile_roles[role] = profile_roles.get(role, 0) + count
    report = {
        "suite": "kubeflow-tpu-conformance",
        "passed": all(r["passed"] for r in results),
        "checks": results,
        "profile": {
            "samples": sum(profile_roles.values()),
            "roles": dict(sorted(profile_roles.items())),
            "errors": prof.errors,
        },
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
    print(f"{'PASS' if report['passed'] else 'FAIL'}: "
          f"{sum(r['passed'] for r in results)}/{len(results)} checks "
          f"(report: {args.report})")
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
