#!/usr/bin/env python3
"""Fleet-scale load test of the controller runtime (VERDICT r4 item 3).

The reference inherits controller-runtime's maturity (reference
notebook-controller/controllers/notebook_controller.go:647-733 — the
predicate/watch machinery this repo's Python watch→workqueue→reconcile
engine replaces); its functional tests cap at 8 notebooks.  This bench
answers the scale question directly: create a WAVE of N notebooks against
the in-memory apiserver with a kubelet simulator bringing worker pods
Running, and measure

* time-to-all-converged (every Notebook status fully ready),
* peak workqueue backlog (queue.pending() sampled at 10 ms),
* a full steady-state RESYNC cycle (list N + enqueue N + reconcile N
  no-ops) — wall and process-CPU seconds,
* sustained CHURN (annotation touches at a fixed rate) — drain check,
* process RSS growth across the run,

at two fleet sizes (default 150 and 600), and asserts near-linear
scaling: per-notebook converge time at the large fleet must stay within
SCALE_BAND x the small fleet's (superlinear blowup — an O(N^2) resync,
deep-copy amplification on the event path — is exactly what functional
tests cannot see).

Protocol notes: the controller runs with workers=4 (now also the
platform-wide dispatch default — CONTROLLER_WORKERS; 4 matches the
race-stress tier and a production controller-runtime
MaxConcurrentReconciles).  The workers sweep + wire-converge phases
(run_worker_sweep) measure the parallel-dispatch win itself over the
HTTP transport.  The kubelet sim acks StatefulSets from a
watch, so pod bring-up latency scales with the fleet the way a real
cluster's would (per-STS, not per-wave).  Everything is event-driven;
convergence is observed from the NOTEBOOK watch stream, not by polling
lists.

Output: one JSON line per metric (bench.py convention), all lines carry
band/band_floor self-reporting (VERDICT r4 item 2 discipline).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time

# Baselines re-pinned 2026-08-04 on the current 2-CPU dev container after
# the zero-copy frozen-view read path landed (informer reads return
# read-only views instead of deep copies; resync enqueues key-only;
# reconcilers read secondaries from the caches).  Same-machine
# before/after: 600-object steady-state resync 1.67 -> 0.48 s CPU (-71%),
# informer get() 62k -> 140k/s, list() 61k -> 1.5M objs/s — see
# BASELINE.md "Control-plane fleet scale" and docs/performance.md.
# ``fleet_resync_cpu_s`` is the MIN CPU over three steady-state cycles
# (first-cycle warmup and scheduler noise dominated a single sample).
# The bands stay loose (3x) — shared-CPU container; the tripwire is for
# order-of-magnitude regressions (an accidental O(N^2) or a return of
# copy-per-read), not scheduler noise.
# fleet_converge and resync_cpu re-pinned 2026-08-04 after the parallel-
# dispatch + write-coalescing PR (workers=4 default, FlightPool secondary
# fan-out, diff-and-patch writes): same-machine 600-notebook wave
# converge 6.0 -> 2.2 ms/notebook and steady-state resync CPU
# 0.55 -> 0.19 s measured on the 2-CPU container.
# Whole dict re-pinned 2026-08-04 (same 2-CPU container, one run)
# alongside the NEW sharded-HA bands (ISSUE 9) so the report stays one
# coherent same-machine trajectory: converge 2.54 ms/nb, resync CPU
# 0.237 s, cached gets 212k/s (the ownership-filter hook costs nothing
# when sharding is off), alloc 0.64 KiB/obj, wire converge 6.35 s.
BASELINE = {
    "fleet_converge_ms_per_notebook": 2.5,    # 600-notebook wave
    "fleet_resync_cpu_s": 0.24,               # min of 3 600-object cycles
    # Read-path microbench (zero-copy frozen views): informer get()
    # throughput and the resync cycle's peak tracemalloc footprint per
    # object.  Pre-frozen-view: ~62k gets/s and ~3 KB/object of copy
    # churn on this container.
    "cached_get_per_s": 200_000.0,            # 600-object store
    # Re-pinned 0.65 -> 0.85 (2026-08-04, same container) when causal
    # journey tracing (ISSUE 14) landed: the per-reconcile context
    # machinery adds a small, mostly-fixed footprint (measured 0.845 at
    # 600 objects; resync CPU unchanged at 0.238 s).  Copy-per-read
    # amplification is PER-OBJECT and still trips the 3x band.
    "resync_alloc_peak_kb_per_obj": 0.85,     # tracemalloc peak / N
}
BAND_FACTOR = 3.0
# Large-fleet per-notebook converge time must stay within this factor of
# the small fleet's (near-linear scaling).
SCALE_BAND = 2.0
# Chaos band (ISSUE 4): an 80-notebook wave under the standard seeded
# fault storm (testing.chaos.storm at CHAOS_RATE) must still converge
# with ZERO dead-letters inside BAND_FACTOR x this baseline.  The banded
# value is ABSOLUTE storm converge seconds, not storm/clean ratio — the
# clean wave is ~0.1 s while the storm floor is dominated by the
# workqueue's (deliberate) backoff delays, so a ratio would measure the
# backoff constants, not regressions.  clean_converge_s rides along in
# the line so happy-path overhead of the resilience machinery stays
# visible next to the existing converge band.
CHAOS_SEED = 20260804
CHAOS_RATE = 0.05
# 80-notebook storm on the 2-CPU container; the banded value is the MIN
# over run_chaos's storm samples (the storm tail is a backoff lottery —
# whether the last key draws a near-max backoff; single samples measured
# 7-79 s on identical code, so the min is the one-sided-noise statistic,
# same as the resync-CPU protocol).  Re-pinned 12.0 -> 7.0 for the
# write-coalesced path: merge patches carry no resourceVersion, so the
# storm's 409-on-update faults have almost nothing left to hit —
# alternating same-machine A/B measured storm converge 11.7-52.2 s on
# the full-update path vs 3.2-7.3 s on the patched path.  The 2026-08-04
# re-pin run measured min-of-2 12.7 s (samples 23.5/12.7) — inside the
# 3x band; deliberately NOT re-pinned upward: the storm tail is the
# documented backoff lottery and loosening the tripwire to one noisy
# draw would blunt it.
CHAOS_CONVERGE_BASELINE_S = 7.0
# Parallel-dispatch bands (ISSUE 5): the wave-converge-vs-workers sweep
# and the wire-level converge both run over the HTTP transport — parallel
# dispatch exists to overlap blocking apiserver round trips, which the
# in-memory fake doesn't have (its reconciles are GIL-bound CPU where
# extra workers can't help).  K8S_CLIENT_QPS is forced to 0 for these
# phases: the default 50-QPS limiter throttles every arm to the same rate
# and would measure the limiter, not the dispatch.
WORKER_SWEEP_WORKERS = (1, 4)
WORKER_SWEEP_MIN_SPEEDUP = 1.3   # workers=4 must beat workers=1 by >=30%
WORKER_SWEEP_RTT_S = 0.002       # injected per-call apiserver RTT
WIRE_CONVERGE_BASELINE_S = 6.4   # 80-nb wave, http, workers=4, QPS off
                                 # (re-pinned 2026-08-04: measured 6.35)
# Sharded HA bands (ISSUE 9): a 10k-notebook wave across 4 simulated
# replicas (runtime/sharding.py lease-owned keyspace shards over one
# FakeKube; testing/shardfleet.py harness).  The converge band is
# absolute wall seconds — in ONE process the replicas share the GIL, so
# sharding buys no CPU here; what it buys (and what the second band
# pins) is per-replica watch/cache load: each replica's informers hold
# and process only its owned ranges, so the LARGEST per-replica cache
# must stay under SHARDED_CACHE_FRAC_MAX of the full keyspace a
# single-process controller would hold (4 replicas ≈ 0.25 + rebalance
# slop).  The fencing invariant (no key written by two replicas in
# overlapping ownership windows) is asserted on every bench run — a
# perf harness that silently stopped exercising the fence would be
# worthless as a regression tripwire.
SHARDED_REPLICAS = 4
SHARDED_SHARDS = 8
SHARDED_LEASE_S = 2.0
# Pinned 2026-08-04 on the 2-CPU dev container, full-run protocol (the
# sharded phase runs after the fleet/chaos/sweep phases, in their
# process): 10k-notebook wave over 4 replicas converged in 90.4 s
# (9.0 ms/notebook; 65.3 s when run standalone — accumulated process
# state, not algorithmic; the single-process 600-notebook band runs
# ~2.5 ms/notebook and the remaining gap is 4x informer sets + lease
# traffic sharing one GIL).  Same run: max per-replica cache 12.5k objs
# of a 70k full keyspace (0.179), mean admit fraction 0.17, 88,930
# fenced writes checked clean.
SHARDED_CONVERGE_BASELINE_S = 90.0
SHARDED_CACHE_FRAC_MAX = 0.5
# TPUJob queue band (ISSUE 11): admission-decision throughput over the
# runtime/jobqueue.py ledger with 1k pending jobs across 3 profiles.  The
# drain loop touches only the queue HEAD region per decision (sorted
# index + incremental pool/quota tallies), so throughput must stay flat
# in queue depth — a rescan-per-event regression shows up as an
# order-of-magnitude drop.  Pinned 2026-08-04 on the 2-CPU dev container:
# 1k-job drain measured ~21k decisions/s (max-of-3 passes; each loop
# iteration = head decision + one wait-path decision + admit/complete
# bookkeeping through full job-dict parses).  Depth scaling measured
# 250/1k/4k jobs -> 33k/29k/19k per s: the mild decay is the sorted
# index's head-delete memmove (C-speed, linear in bytes), not a rescan —
# a true O(queue) decision loop would decay 16x over that range.
JOBQUEUE_JOBS = 1000
JOBQUEUE_PROFILES = 3
JOBQUEUE_DECISIONS_BASELINE = 20_000.0
# InferenceService autoscale band (ISSUE 12): 50 services ride one
# traffic wave — synthetic deep-queue /metrics pages through the
# controller's REAL scrape+decide path, pods simulated by
# InferenceFleetSim — and the banded value is seconds until EVERY
# service's replica count matches its target (1→4 on the wave, with the
# drain back to 1 reported alongside), zero dead-letters required.
# Pinned 2026-08-04 on the 2-CPU dev container: 50-service wave converged
# in ~0.48 s up / ~0.59 s down across repeated runs (sync_period 0.1 s;
# the down leg pays one extra halving step, 4→2→1).  Banded at the usual
# loose 3x — the tripwire is a scrape/decide path going per-service-
# serial or O(fleet) per reconcile, not scheduler noise.
INFERENCE_SERVICES = 50
INFERENCE_SCALE_BASELINE_S = 0.7
# Fleet metrics pipeline band (ISSUE 15): ``--fleetscrape-targets``
# synthetic replica /metrics pages (serve gauges + 8-bucket TTFT
# histogram + counters, ~19 samples each) through the REAL pipeline —
# FleetScraper fan-out on the FlightPool, prometheus-text parse, TSDB
# store, then a burn-rate rule evaluation per pass — and the banded
# value is stored samples per second across the whole loop.  Pinned
# 2026-08-05 on the 2-CPU dev container: 200 targets x 4 passes sustain
# ~46-54k samples/s best-of-3 (parse-dominated; the TSDB's per-name
# series index keeps rule evaluation off the store-scan path).  Banded
# at the usual loose 3x: the tripwire is a parse-per-rule or
# store-rescan regression going O(series) per sample, not scheduler
# noise.
FLEETSCRAPE_TARGETS = 200
FLEETSCRAPE_SAMPLES_BASELINE = 45_000.0

# Wire-codec decode bands (ISSUE 18): the native watch-line fast path
# (native/wirecodec.cc scanner through k8s/codec.decode_event) against
# the pure-Python json.loads leg, A/B over the same corpus of realistic
# ~3 KB pod watch lines (full status/conditions/containerStatuses — the
# object size a 100k-object fleet actually streams).  Each leg decodes
# and then reads the three metadata identity fields (name / namespace /
# resourceVersion), exactly the admit+dedup touch pattern, so the native
# leg's laziness is measured at the honest boundary — identity reads
# answer from the scanner's extracted fields without any Python JSON
# parse.  Measured 2026-08-06 on the 2-CPU dev container: python ~35k
# events/s, native ~170k events/s (4.9x).  Two gates: the usual 3x
# throughput band on the native leg, AND the in-run speedup itself must
# hold DECODE_SPEEDUP_MIN — a regression that slowed both legs equally
# would slip a throughput-only band on a faster machine.
DECODE_AB_EVENTS = 1500
DECODE_SPEEDUP_MIN = 3.0
DECODE_EPS_BASELINE = 150_000.0
# Server-side shard filtering band (ISSUE 18): with ShardFilter
# subscriptions pushed into watch/list, each of the 4 replicas' streams
# should carry only ~1/4 of the informer-kind events plus rebalance
# replay and fail-open deliveries (involved-source Events without a
# derivable key, unfiltered startup streams).  The banded value is the
# MEAN per-replica fraction of emitted informer-kind events actually
# decoded (measured stable ~0.28 at smoke size); the per-replica MAX
# rides along unbanded — at 24-name smoke waves the shard hash lottery
# swings single replicas to ~0.42 on identical code.  Before server-side
# filtering every replica decoded the full stream (fraction 1.0), so the
# <1.0 assertion alone already proves the wall came down; 0.35 bounds
# the slop.
DECODE_FRACTION_MAX = 0.35

# Always-on profiler overhead band (ISSUE 16): sampler-on vs sampler-off
# fleet-converge waves, min-of-N per arm.  The budget is 5% — the design
# point that justifies running the sampler ALWAYS (GWP lineage): at
# 67 Hz a sampling pass walks sys._current_frames() over a few dozen
# threads and folds ~24 frames each, comfortably under the budget; the
# band trips if stack folding or attribution ever lands on a hot path.
PROFILE_OVERHEAD_BAND_PCT = 5.0
PROFILE_FLEET = 80


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


class FleetHarness:
    """Notebook controller + kubelet sim against the in-memory apiserver.

    ``transport="http"`` interposes the real REST client against the fake
    served over HTTP (the envtest analogue, as ci/e2e.py does) so the
    controller, its informers and their resourceVersion-resumed watches
    all cross a real wire; ``watch_window`` shrinks the client's bounded
    watch windows so the resume/replay path (FakeKube event history, 410
    on compaction) is exercised MANY times during a wave instead of once
    per 300 s."""

    def __init__(self, *, workers: int = 4, transport: str = "memory",
                 watch_window: float = None, chaos_seed: int = None,
                 chaos_rate: float = CHAOS_RATE, chaos_faults: list = None):
        import logging

        from kubeflow_tpu.platform.controllers.notebook import make_controller
        from kubeflow_tpu.platform.testing import FakeKube

        logging.getLogger("kubeflow_tpu.runtime").setLevel(logging.ERROR)
        logging.getLogger("werkzeug").setLevel(logging.ERROR)
        self.kube = FakeKube()
        self.kube.add_namespace("fleet")
        self.kube.add_tpu_node("tpu-node-1", topology="2x4")
        from kubeflow_tpu.platform.testing.httpkube import make_transport

        self.api_client, self.http_server = make_transport(
            self.kube, transport, watch_window=watch_window)
        # chaos_seed is not None: the controller's entire apiserver path
        # runs through a seeded ChaosKube storm (the kubelet/convergence
        # sims keep talking to the healthy store — only the control plane
        # flakes), for the ctrlplane_chaos_converge_s band.
        # chaos_faults overrides the schedule (e.g. the worker sweep's
        # pure-latency RTT model).
        self.chaos = None
        if chaos_seed is not None or chaos_faults is not None:
            from kubeflow_tpu.platform.testing.chaos import ChaosKube, storm

            faults = (chaos_faults if chaos_faults is not None
                      else storm(rate=chaos_rate))
            self.chaos = ChaosKube(self.api_client, faults,
                                   seed=chaos_seed or 0)
            self.api_client = self.chaos
        self.ctrl = make_controller(self.api_client, use_istio=False)
        self.ctrl.workers = workers
        self._stop = threading.Event()
        self._converged: set = set()
        self._converged_lock = threading.Lock()
        self._conv_event = threading.Event()
        self._target = 0
        self._peak_depth = 0
        self._threads = []
        for fn in (self._kubelet_loop, self._convergence_loop,
                   self._depth_sampler):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        self.ctrl.start(self.api_client)

    def close(self):
        self._stop.set()
        self.ctrl.stop()
        for t in self._threads:
            t.join(timeout=5)
        if self.http_server is not None:
            self.http_server.stop()

    # -- simulators ----------------------------------------------------------

    def _kubelet_loop(self):
        """Bring every StatefulSet's pods Running, from the STS watch (the
        cluster side of the spawn path — ci/e2e.py:_kubelet_sim, scaled)."""
        from kubeflow_tpu.platform.k8s import errors
        from kubeflow_tpu.platform.k8s.types import STATEFULSET, deep_get

        acked = {}
        for _etype, sts in self.kube.watch(STATEFULSET, "fleet",
                                           stop=self._stop):
            name = sts["metadata"]["name"]
            replicas = deep_get(sts, "spec", "replicas", default=0)
            if acked.get(name) == replicas or not replicas:
                continue
            tmpl = deep_get(sts, "spec", "template")
            for i in range(replicas):
                pod = {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {
                        "name": f"{name}-{i}", "namespace": "fleet",
                        "labels": dict(
                            deep_get(tmpl, "metadata", "labels",
                                     default={}) or {}),
                    },
                    "spec": deep_get(tmpl, "spec"),
                }
                try:
                    self.kube.create(pod)
                except errors.AlreadyExists:
                    pass
                try:
                    self.kube.set_pod_phase("fleet", f"{name}-{i}",
                                            "Running", ready=True)
                except errors.ApiError:
                    pass
            acked[name] = replicas

    def _convergence_loop(self):
        """Track fully-ready notebooks from the NOTEBOOK watch stream."""
        from kubeflow_tpu.platform.k8s.types import NOTEBOOK, deep_get

        for _etype, nb in self.kube.watch(NOTEBOOK, "fleet",
                                          stop=self._stop):
            ready = deep_get(nb, "status", "readyReplicas", default=0)
            reps = deep_get(nb, "status", "replicas", default=0)
            if reps and ready == reps:
                with self._converged_lock:
                    self._converged.add(nb["metadata"]["name"])
                    if (self._target
                            and len(self._converged) >= self._target):
                        self._conv_event.set()

    def _depth_sampler(self):
        while not self._stop.wait(0.01):
            d = self.ctrl.queue.pending()
            if d > self._peak_depth:
                self._peak_depth = d

    # -- phases --------------------------------------------------------------

    def wave(self, n: int, *, timeout: float = 300.0,
             prefix: str = "nb") -> dict:
        """Create n notebooks back-to-back; wait for all to converge.
        ``prefix`` lets successive waves in one harness coexist."""
        with self._converged_lock:
            self._target = n + len(self._converged)
            # Same lock as _convergence_loop's set(): a stale event from
            # the previous wave must not satisfy this wave's wait.
            self._conv_event.clear()
        t0 = time.perf_counter()
        cpu0 = time.process_time()
        for i in range(n):
            self.kube.create({
                "apiVersion": "kubeflow.org/v1beta1", "kind": "Notebook",
                "metadata": {"name": f"{prefix}-{i:04d}",
                             "namespace": "fleet"},
                "spec": {
                    "tpu": {"accelerator": "v5e", "topology": "2x4"},
                    "template": {"spec": {"containers": [
                        {"name": "notebook",
                         "image": "ghcr.io/kubeflow-tpu/jupyter-jax-tpu"}]}},
                },
            })
        create_s = time.perf_counter() - t0
        if not self._conv_event.wait(timeout):
            with self._converged_lock:
                missing = self._target - len(self._converged)
            raise TimeoutError(
                f"{missing}/{n} notebooks unconverged after {timeout}s "
                f"(queue depth {self.ctrl.queue.pending()})")
        out = {
            "converge_s": time.perf_counter() - t0,
            "create_s": create_s,
            "cpu_s": time.process_time() - cpu0,
            "peak_queue_depth": self._peak_depth,
            "reconciles": self.ctrl.reconcile_count,
            "errors": self.ctrl.error_count,
        }
        # Causal segment breakdown (telemetry/critical_path.py): decompose
        # the LAST-created notebook's journey — the last journey's spans
        # are guaranteed inside the bounded store even at large N — into
        # the named segments (watch_lag / queue_wait / reconcile /
        # write_rtt ...) so the converge band says WHERE the ms/notebook
        # goes, not just how many.
        from kubeflow_tpu.platform.k8s.types import NOTEBOOK

        segments = _journey_segments(
            self.kube, NOTEBOOK, f"{prefix}-{n - 1:04d}", "fleet")
        if segments is not None:
            out["segments"] = segments
        return out

    def resync_cycle(self, *, timeout: float = 120.0) -> dict:
        """One full steady-state resync: enqueue every primary key, drain
        the no-op reconciles.  This is the periodic cost a fleet pays
        forever (the controller's resync_period loop) — the place an
        O(N^2) hides.  Runs the controller's own pass
        (Controller._resync_once): a key-only cache read (Informer.keys)
        that enqueues N requests without materializing or copying N
        objects."""
        base = self.ctrl.reconcile_count
        t0 = time.perf_counter()
        cpu0 = time.process_time()
        # The controller's own pass reports how many keys it enqueued, so
        # the drain target can never disagree with what actually queued.
        n = self.ctrl._resync_once(self.api_client)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (self.ctrl.queue.pending() == 0
                    and self.ctrl.reconcile_count >= base + n):
                break
            time.sleep(0.005)
        else:
            raise TimeoutError(f"resync of {n} notebooks did not drain")
        return {
            "n": n,
            "wall_s": time.perf_counter() - t0,
            "cpu_s": time.process_time() - cpu0,
        }

    def read_microbench(self, *, seconds: float = 0.5) -> dict:
        """Cached-read throughput straight off the informer store: get()
        by key and full list() sweeps, both returning zero-copy frozen
        views.  The pre-frozen-view informer deep-copied every result, so
        this is the microbench that pins the read-path win."""
        from kubeflow_tpu.platform.k8s.types import NOTEBOOK

        informer = self.ctrl.informers[NOTEBOOK]
        names = [name for _, name in informer.keys("fleet")]
        t0 = time.perf_counter()
        gets = 0
        while time.perf_counter() - t0 < seconds:
            informer.get(names[gets % len(names)], "fleet")
            gets += 1
        gets_per_s = gets / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        listed = 0
        while time.perf_counter() - t0 < seconds:
            listed += len(informer.list("fleet"))
        list_objs_per_s = listed / (time.perf_counter() - t0)
        return {
            "get_per_s": gets_per_s,
            "list_objs_per_s": list_objs_per_s,
        }

    def resync_alloc(self, *, timeout: float = 120.0) -> dict:
        """One resync cycle under tracemalloc: peak allocated bytes and
        net live blocks across the pass.  Copy-amplification (the
        pre-frozen-view O(fleet x object-size) deep copies per resync)
        shows up directly as peak growth; run separately from the timed
        cycle because tracemalloc slows every allocation."""
        import gc
        import tracemalloc

        gc.collect()
        tracemalloc.start()
        try:
            base_current, _ = tracemalloc.get_traced_memory()
            tracemalloc.reset_peak()
            snap_before = tracemalloc.take_snapshot()
            res = self.resync_cycle(timeout=timeout)
            snap_after = tracemalloc.take_snapshot()
            _, peak = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
        net_blocks = sum(
            d.count_diff for d in snap_after.compare_to(snap_before, "filename")
            if d.count_diff > 0)
        return {
            "n": res["n"],
            "peak_kb": (peak - base_current) / 1024.0,
            "peak_kb_per_obj": (peak - base_current) / 1024.0 / max(res["n"], 1),
            "net_blocks": net_blocks,
        }

    def churn(self, *, seconds: float = 3.0, rate_hz: float = 200.0) -> dict:
        """Steady-state touches (annotation updates) at rate_hz; the queue
        must keep draining (backlog bounded, no error growth)."""
        import random

        from kubeflow_tpu.platform.k8s import errors
        from kubeflow_tpu.platform.k8s.types import NOTEBOOK

        names = [nb["metadata"]["name"]
                 for nb in self.kube.list(NOTEBOOK, "fleet")]
        rng = random.Random(0)
        base_err = self.ctrl.error_count
        depth_samples = []
        n_touches = 0
        t0 = time.perf_counter()
        period = 1.0 / rate_hz
        while time.perf_counter() - t0 < seconds:
            name = rng.choice(names)
            try:
                nb = self.kube.get(NOTEBOOK, name, "fleet")
                nb["metadata"].setdefault("annotations", {})["touch"] = (
                    str(n_touches))
                self.kube.update(nb)
                n_touches += 1
            except errors.ApiError:
                pass
            depth_samples.append(self.ctrl.queue.pending())
            deadline = t0 + n_touches * period
            lag = deadline - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
        # drain
        deadline = time.monotonic() + 30.0
        while (self.ctrl.queue.pending() > 0
               and time.monotonic() < deadline):
            time.sleep(0.005)
        depth_samples.sort()
        return {
            "touches": n_touches,
            "achieved_hz": n_touches / (time.perf_counter() - t0),
            "p95_queue_depth":
                depth_samples[int(len(depth_samples) * 0.95)]
                if depth_samples else 0,
            "drained": self.ctrl.queue.pending() == 0,
            "new_errors": self.ctrl.error_count - base_err,
        }


def _journey_segments(kube, gvk, name: str, namespace: str):
    """Critical-path segment breakdown of one object's causal journey
    for a bench line, or None.  Best-effort BY DESIGN: a chaos wave may
    sever a journey mid-storm and a missing breakdown must not fail the
    bench — ci/bench_smoke.py is the loud gate that the keys ride the
    clean runs."""
    from kubeflow_tpu.telemetry import causal, critical_path

    try:
        ctx = causal.from_object(kube.get(gvk, name, namespace))
        if ctx is None:
            return None
        return critical_path.segment_summary(causal.journey(ctx.trace_id))
    except Exception:
        return None


# vs_baseline convention across EVERY metric line: > 1.0 means better
# than baseline (baseline/value for lower-is-better metrics like CPU and
# allocations, value/baseline for throughput) — tooling trending the
# field can compare lines without knowing each metric's direction.
def _band(value: float, baseline: float) -> str:
    return "pass" if value <= baseline * BAND_FACTOR else "REGRESSION"


def _band_min(value: float, baseline: float) -> str:
    """Band for higher-is-better metrics (throughput)."""
    return "pass" if value >= baseline / BAND_FACTOR else "REGRESSION"


def run_fleet(n: int, *, churn_s: float, transport: str = "memory",
              watch_window: float = None, detail: bool = True) -> dict:
    """``detail=False`` (the small comparison fleet) skips the read
    microbench, the tracemalloc pass, and the min-of-3 resync protocol —
    main() only reads the small fleet's wave numbers, so that work would
    be paid and discarded."""
    from kubeflow_tpu.platform.runtime import metrics as rtmetrics

    h = FleetHarness(transport=transport, watch_window=watch_window)
    try:
        rss0 = _rss_mb()
        # Reconcile latency comes from the controller_runtime histogram the
        # runtime now exports; the pre-wave snapshot diffs out observations
        # from earlier fleets in this process (the registry is
        # process-global by design).
        snap = rtmetrics.histogram_snapshot(
            rtmetrics.controller_runtime_reconcile_time_seconds,
            {"controller": h.ctrl.name},
        )
        wave = h.wave(n)
        quantiles = rtmetrics.reconcile_quantiles(
            h.ctrl.name, (0.5, 0.99), since=snap)
        wave["reconcile_p50_ms"] = (
            round(quantiles[0.5] * 1e3, 3)
            if quantiles[0.5] is not None else None)
        wave["reconcile_p99_ms"] = (
            round(quantiles[0.99] * 1e3, 3)
            if quantiles[0.99] is not None else None)
        if detail:
            # Three steady-state cycles, keep the cheapest: cycle one pays
            # lazy-import/JIT warmup and the wave's settling churn, and a
            # 2-CPU shared container adds scheduler noise a single sample
            # can't average out.  Min is the right statistic for "what
            # does this code cost" under one-sided noise.
            cycles = [h.resync_cycle() for _ in range(3)]
            resync = min(cycles, key=lambda c: c["cpu_s"])
            resync["cycles_cpu_s"] = [round(c["cpu_s"], 3) for c in cycles]
            reads = h.read_microbench()
            alloc = h.resync_alloc()
        else:
            resync = h.resync_cycle()
            reads = alloc = None
        churn = h.churn(seconds=churn_s)
        rss1 = _rss_mb()
    finally:
        h.close()
    return {"wave": wave, "resync": resync, "reads": reads, "alloc": alloc,
            "churn": churn,
            "rss_mb_before": round(rss0, 1), "rss_mb_after": round(rss1, 1)}


def run_chaos(n: int, *, seed: int = CHAOS_SEED, rate: float = CHAOS_RATE,
              transport: str = "memory", storms: int = 2) -> dict:
    """The resilience band: one clean wave and ``storms`` seeded-storm
    waves of the same fleet, reporting the MIN storm converge (plus every
    sample), faults injected, and dead-letters (must be 0 — the storm is
    transient).  Min-of-N, like the resync-CPU protocol: the storm tail
    is a backoff lottery — whether the last key draws a near-max backoff
    right before converging — measured here swinging 7-79 s on IDENTICAL
    code, so a single sample would band the dice, not the code."""
    import logging

    clean = FleetHarness(transport=transport)
    try:
        clean_s = clean.wave(n)["converge_s"]
    finally:
        clean.close()
    samples, injected, dead_letters, errors = [], 0, 0, 0
    # Injected faults log as reconcile errors by design; hundreds of
    # expected tracebacks would bury the metric lines.
    logging.getLogger("kubeflow_tpu.runtime").setLevel(logging.CRITICAL)
    try:
        for i in range(max(1, storms)):
            stormy = FleetHarness(transport=transport, chaos_seed=seed + i,
                                  chaos_rate=rate)
            try:
                wave = stormy.wave(n)
                samples.append(wave["converge_s"])
                injected += stormy.chaos.injected()
                dead_letters += len(stormy.ctrl.dead_letters)
                errors += wave["errors"]
            finally:
                stormy.close()
    finally:
        logging.getLogger("kubeflow_tpu.runtime").setLevel(logging.ERROR)
    best = min(samples)
    return {
        "fleet": n,
        "clean_converge_s": round(clean_s, 3),
        "storm_converge_s": round(best, 3),
        "storm_samples_s": [round(s, 3) for s in samples],
        "overhead_x": round(best / max(clean_s, 1e-9), 3),
        "faults_injected": injected,
        "dead_letters": dead_letters,
        "reconcile_errors": errors,
    }


def run_profile_overhead(n: int, *, rounds: int = 2,
                         waves: int = 8) -> dict:
    """The always-on-profiler guard (ISSUE 16): A/B fleet-converge arms
    with the sampler off vs on (default KFT_PROFILE_HZ, registered like
    production so every attribution seam is live).  The BANDED number is
    CPU-accounted, not wall-clock: the sampler meters its own thread CPU
    (``Profiler.sampler_cpu_seconds``, ``time.thread_time`` deltas
    around each pass), and ``overhead_pct`` = sampler CPU burnt during
    the timed waves / converge CPU everything else burnt.  Both sides
    come off CPU clocks, so a 2-CPU shared container's scheduler jitter
    — which swings single-wave wall time ±30% on identical code, far
    more than a 67 Hz sampler ever could — cancels out of the band.
    The wall-clock A/B legs (min over rounds of a ``waves``-wave
    amortised arm) ride along as evidence that "on" does not regress
    converge beyond that same noise.  The band also requires
    samples > 0 — a sampler that silently never ran would otherwise
    band at a perfect 0%."""
    from kubeflow_tpu.telemetry import profiler as profiler_mod

    off_s, on_s = [], []
    sampler_cpu = work_cpu = 0.0
    samples = 0
    roles = set()
    for i in range(max(1, rounds)):
        for arm in ("off", "on"):
            prof = None
            if arm == "on":
                prof = profiler_mod.Profiler()
                prof.start()
                profiler_mod.register_debug_profiler(prof)
            h = FleetHarness()
            try:
                wall = cpu = scpu = 0.0
                for w in range(max(1, waves)):
                    c0 = prof.sampler_cpu_seconds if prof else 0.0
                    out = h.wave(n, prefix=f"prof-{arm}{i}-{w}")
                    wall += out["converge_s"]
                    cpu += out["cpu_s"]
                    if prof is not None:
                        scpu += prof.sampler_cpu_seconds - c0
            finally:
                h.close()
                if prof is not None:
                    prof.stop()
                    profiler_mod.register_debug_profiler(None)
            if prof is not None:
                for w in prof.windows():
                    samples += w["samples"]
                    for line in (prof.folded(w["window"]) or "").splitlines():
                        roles.add(line.split(";", 1)[0])
                on_s.append(wall)
                sampler_cpu += scpu
                # wave() meters process CPU, which includes the sampler
                # thread — subtract it so the ratio is sampler vs work.
                work_cpu += max(cpu - scpu, 1e-9)
            else:
                off_s.append(wall)
    best_off, best_on = min(off_s), min(on_s)
    return {
        "fleet": n,
        "waves": waves,
        "overhead_pct": round(sampler_cpu / max(work_cpu, 1e-9) * 100.0, 2),
        "sampler_cpu_s": round(sampler_cpu, 4),
        "converge_cpu_s": round(work_cpu, 4),
        "converge_off_s": round(best_off, 3),
        "converge_on_s": round(best_on, 3),
        "off_samples_s": [round(s, 3) for s in off_s],
        "on_samples_s": [round(s, 3) for s in on_s],
        "profile_samples": samples,
        "roles": sorted(roles),
    }


def _watch_line(i: int) -> bytes:
    """One realistic pod watch line (~3 KB): full spec with tolerations,
    volumes, probes, and a Running status with conditions and
    containerStatuses — the shape and size a real kubelet-fed apiserver
    streams at fleet scale.  Deterministic in ``i`` so both A/B legs and
    repeated runs decode the identical corpus."""
    nb = f"nb-{i % 24}"
    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": f"{nb}-0", "namespace": f"user{i % 5}",
            "uid": f"8f2c{i:08d}-aaaa-bbbb-cccc-000000000000",
            "resourceVersion": str(100000 + i),
            "creationTimestamp": "2026-08-06T01:02:03Z",
            "labels": {"notebook-name": nb, "app": "notebook",
                       "statefulset": nb,
                       "controller-revision-hash": f"{nb}-7b9df"},
            "annotations": {
                "kubeflow.org/creator": f"user{i % 5}@example.com",
                "kubernetes.io/config.seen":
                    "2026-08-06T01:02:04.123456789Z",
                "prometheus.io/scrape": "true",
                "prometheus.io/port": "8888"},
            "ownerReferences": [{
                "apiVersion": "apps/v1", "kind": "StatefulSet",
                "name": nb, "uid": f"11112222-3333-4444-5555-{i:012d}",
                "controller": True, "blockOwnerDeletion": True}],
        },
        "spec": {
            "nodeName": f"tpu-node-{i % 16}",
            "serviceAccountName": "default-editor",
            "schedulerName": "default-scheduler",
            "tolerations": [
                {"key": "google.com/tpu", "operator": "Exists",
                 "effect": "NoSchedule"},
                {"key": "node.kubernetes.io/not-ready",
                 "operator": "Exists", "effect": "NoExecute",
                 "tolerationSeconds": 300},
                {"key": "node.kubernetes.io/unreachable",
                 "operator": "Exists", "effect": "NoExecute",
                 "tolerationSeconds": 300}],
            "volumes": [
                {"name": "workspace", "persistentVolumeClaim":
                    {"claimName": f"workspace-{nb}"}},
                {"name": "dshm", "emptyDir": {"medium": "Memory"}},
                {"name": "kube-api-access", "projected": {"sources": [
                    {"serviceAccountToken": {"expirationSeconds": 3607,
                                             "path": "token"}}]}}],
            "containers": [{
                "name": "notebook",
                "image": "jupyter/tensorflow-notebook:v1.8",
                "command": ["jupyter"], "args": ["lab", "--ip=0.0.0.0"],
                "ports": [{"containerPort": 8888,
                           "name": "notebook-port", "protocol": "TCP"}],
                "env": [
                    {"name": "NB_PREFIX",
                     "value": f"/notebook/user{i % 5}/{nb}"},
                    {"name": "JUPYTER_ENABLE_LAB", "value": "yes"},
                    {"name": "TPU_WORKER_ID", "value": str(i % 8)}],
                "resources": {
                    "limits": {"cpu": "4", "memory": "16Gi",
                               "google.com/tpu": "8"},
                    "requests": {"cpu": "2", "memory": "8Gi",
                                 "google.com/tpu": "8"}},
                "volumeMounts": [
                    {"name": "workspace", "mountPath": "/home/jovyan"},
                    {"name": "dshm", "mountPath": "/dev/shm"},
                    {"name": "kube-api-access", "readOnly": True,
                     "mountPath": "/var/run/secrets/"
                                  "kubernetes.io/serviceaccount"}],
                "livenessProbe": {
                    "httpGet": {"path": "/api", "port": 8888},
                    "initialDelaySeconds": 10, "periodSeconds": 5},
                "imagePullPolicy": "IfNotPresent",
                "terminationMessagePath": "/dev/termination-log"}],
            "restartPolicy": "Always", "dnsPolicy": "ClusterFirst",
            "terminationGracePeriodSeconds": 30,
        },
        "status": {
            "phase": "Running",
            "podIP": f"10.4.{i % 256}.{(i * 7) % 256}",
            "hostIP": f"10.0.0.{i % 16}", "qosClass": "Burstable",
            "startTime": "2026-08-06T01:02:05Z",
            "conditions": [
                {"type": t, "status": "True", "lastProbeTime": None,
                 "lastTransitionTime": "2026-08-06T01:02:30Z"}
                for t in ("Initialized", "Ready", "ContainersReady",
                          "PodScheduled")],
            "containerStatuses": [{
                "name": "notebook", "ready": True, "restartCount": 0,
                "started": True,
                "image": "jupyter/tensorflow-notebook:v1.8",
                "imageID": "docker-pullable://jupyter/"
                           "tensorflow-notebook@sha256:" + "ab" * 32,
                "containerID": "containerd://" + "cd" * 32,
                "state": {"running":
                          {"startedAt": "2026-08-06T01:02:20Z"}}}],
        },
    }
    return json.dumps({"type": "MODIFIED", "object": pod},
                      separators=(",", ":")).encode()


def run_decode_ab(n_events: int = DECODE_AB_EVENTS) -> dict:
    """The wire-codec A/B (ISSUE 18): decode the same corpus of
    realistic pod watch lines through both codec engines, each event
    followed by the three metadata identity reads the admit/dedup path
    performs.  Best-of-3 per leg (throughput: max is the one-sided-noise
    statistic, like the jobqueue band).  The python leg always runs —
    it is the denominator of the speedup gate."""
    from kubeflow_tpu.platform import native
    from kubeflow_tpu.platform.k8s import codec

    lines = [_watch_line(i) for i in range(n_events)]
    avg_bytes = sum(len(ln) for ln in lines) / len(lines)

    def leg(engine: str) -> float:
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for ln in lines:
                _etype, obj = codec.decode_event(ln, engine=engine)
                m = obj["metadata"]
                m.get("name")
                m.get("namespace")
                m.get("resourceVersion")
            best = max(best, len(lines) / (time.perf_counter() - t0))
        return best

    python_eps = leg("python")
    native_available = native.available()
    native_eps = leg("native") if native_available else 0.0
    return {
        "events": n_events,
        "avg_line_bytes": round(avg_bytes, 0),
        "python_eps": round(python_eps, 0),
        "native_eps": round(native_eps, 0),
        "speedup_x": round(native_eps / max(python_eps, 1e-9), 2),
        "native_available": native_available,
        "native_load_error": native.load_error(),
    }


def run_sharded(n: int, *, replicas: int = SHARDED_REPLICAS,
                num_shards: int = SHARDED_SHARDS,
                timeout: float = 900.0) -> dict:
    """The sharded-HA band (ISSUE 9): an n-notebook wave across
    ``replicas`` simulated controller replicas, each lease-owning its
    hash-shard ranges with shard-filtered informers and fenced writes.
    Reports converge wall time, per-replica cache/watch load against the
    single-process full-keyspace baseline (= every object of the watched
    kinds, which is exactly what one unsharded controller's informers
    hold), and runs the fencing invariant over every write."""
    import logging

    from kubeflow_tpu.platform.k8s.types import (
        EVENT, NOTEBOOK, POD, PODDISRUPTIONBUDGET, SERVICE, STATEFULSET,
    )
    from kubeflow_tpu.platform.testing.shardfleet import ShardedFleet

    logging.getLogger("kubeflow_tpu.runtime.sharding").setLevel(
        logging.ERROR)
    fleet = ShardedFleet(replicas=replicas, num_shards=num_shards,
                         lease_seconds=SHARDED_LEASE_S,
                         renew_seconds=SHARDED_LEASE_S / 10.0)
    try:
        # Decode-fraction protocol (ISSUE 18): measure the wave against a
        # SETTLED shard map — during initial lease acquisition the
        # subscriptions are still widening and streams replay history, so
        # an unsettled start would charge rebalance churn to the steady
        # state.  Denominator: events the fake broadcast for the kinds
        # the replicas actually inform on (pre-filter — what an
        # unfiltered replica would have had to decode).  Numerator: each
        # replica's informers' events_seen delta (post-filter decodes).
        fleet.wait_stable_shard_map()
        informer_kinds = set()
        for r in fleet.replicas:
            informer_kinds.update(g.kind for g in r.controller.informers)
        emitted0 = {k: fleet.kube.events_emitted.get(k, 0)
                    for k in informer_kinds}
        seen0 = {i: s["events_seen"]
                 for i, s in fleet.cache_stats().items()}
        converge_s = fleet.wave(n, timeout=timeout)
        stats = fleet.cache_stats()
        emitted_delta = sum(
            fleet.kube.events_emitted.get(k, 0) - emitted0[k]
            for k in informer_kinds)
        decode_fracs = [
            (stats[r.index]["events_seen"] - seen0[r.index])
            / max(emitted_delta, 1)
            for r in fleet.replicas]
        # Single-process baseline: a full-keyspace informer set caches
        # every live object of the watched kinds.
        watched = (NOTEBOOK, POD, STATEFULSET, SERVICE,
                   PODDISRUPTIONBUDGET, EVENT)
        full_keyspace = sum(
            len(fleet.kube.list(g, None)) for g in watched)
        cached = [s["cached_objects"] for s in stats.values()]
        seen = [s["events_seen"] for s in stats.values()]
        admitted = [s["events_admitted"] for s in stats.values()]
        fenced_writes = fleet.assert_fencing_invariant()
        shard_map = {r.index: sorted(r.coordinator.owned())
                     for r in fleet.replicas}
    finally:
        fleet.close()
    return {
        "fleet": n,
        "replicas": replicas,
        "num_shards": num_shards,
        "converge_s": round(converge_s, 3),
        "full_keyspace_objs": full_keyspace,
        "replica_cache_objs": cached,
        "replica_cache_frac_max": round(
            max(cached) / max(full_keyspace, 1), 4),
        "replica_events_seen": seen,
        "replica_events_admitted": admitted,
        "replica_admit_frac_mean": round(
            sum(admitted) / max(sum(seen), 1), 4),
        "events_emitted_delta": emitted_delta,
        "replica_decode_fraction": [round(f, 4) for f in decode_fracs],
        "decode_fraction_mean": round(
            sum(decode_fracs) / max(len(decode_fracs), 1), 4),
        "decode_fraction_max": round(max(decode_fracs), 4)
        if decode_fracs else 0.0,
        "fenced_writes_checked": fenced_writes,
        "shard_map": shard_map,
    }


def run_jobqueue(n_jobs: int = JOBQUEUE_JOBS,
                 profiles: int = JOBQUEUE_PROFILES) -> dict:
    """The TPUJob admission-decision microbench (ISSUE 11): fill the
    jobqueue ledger with ``n_jobs`` pending gangs across ``profiles``
    namespaces (mixed priorities, capacity-limited pool + per-profile
    quotas), then drain it — every iteration is one head decision +
    admit + complete, exactly the per-event work the controller does.
    Best-of-3 passes (throughput is higher-is-better, so the max is the
    one-sided-noise statistic — the mirror of the resync-CPU min)."""
    from kubeflow_tpu.platform.runtime.jobqueue import JobQueue

    nodes = [{
        "metadata": {"labels": {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "2x4"}},
        "status": {"capacity": {"google.com/tpu": "8"}},
    } for _ in range(8)]
    quotas = [{
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "kf-resource-quota",
                     "namespace": f"team-{p}"},
        "spec": {"hard": {"google.com/tpu": "32"}},
    } for p in range(profiles)]

    def job(i):
        ns = f"team-{i % profiles}"
        return {
            "apiVersion": "kubeflow.org/v1alpha1", "kind": "TPUJob",
            "metadata": {
                "name": f"qj-{i:05d}", "namespace": ns,
                "creationTimestamp":
                    f"2026-01-01T{i // 3600:02d}:"
                    f"{i // 60 % 60:02d}:{i % 60:02d}Z",
            },
            "spec": {
                "tpu": {"accelerator": "v5e", "topology": "2x4",
                        "slices": 1},
                "template": {"spec": {"containers": [{"name": "w"}]}},
                "priority": (i * 37 % 5 + 1) * 100,
            },
        }

    samples = []
    for _pass in range(3):
        q = JobQueue()
        q.set_nodes(nodes)
        q.set_quotas(quotas)
        t_fill = time.perf_counter()
        for i in range(n_jobs):
            q.observe(job(i))
        fill_s = time.perf_counter() - t_fill
        t0 = time.perf_counter()
        completed = 0
        while completed < n_jobs:
            heads = q.kick_requests(limit=2)
            ns, name = heads[0]
            d = q.decide(ns, name)
            assert d.action == "admit", d
            admitted = job(int(name.split("-")[1]))
            admitted["status"] = {"phase": "Running",
                                  "allocatedSlices": d.slices,
                                  "generation": 0, "restarts": 0}
            q.observe(admitted)
            if len(heads) > 1:
                # One non-head decision per cycle: the wait path (head-
                # of-line check) rides the measured loop too.
                q.decide(*heads[1])
            q.forget(ns, name)  # gang completes; capacity frees
            completed += 1
        drain_s = time.perf_counter() - t0
        samples.append({
            "decisions": q.decisions,
            "drain_s": drain_s,
            "fill_s": fill_s,
            "decisions_per_s": q.decisions / max(drain_s, 1e-9),
        })
    best = max(samples, key=lambda s: s["decisions_per_s"])
    return {
        "n_jobs": n_jobs,
        "profiles": profiles,
        "decisions": best["decisions"],
        "drain_s": round(best["drain_s"], 4),
        "fill_s": round(best["fill_s"], 4),
        "decisions_per_s": round(best["decisions_per_s"], 1),
        "samples_per_s": [round(s["decisions_per_s"], 1)
                          for s in samples],
    }


def run_fleetscrape(n_targets: int = FLEETSCRAPE_TARGETS,
                    passes: int = 4) -> dict:
    """The fleet-metrics-pipeline microbench (ISSUE 15): ``n_targets``
    synthetic replica pages through scrape → parse → TSDB store → rule
    evaluation per pass — the whole decision substrate, measured as
    stored samples per second.  Best-of-3 loops (throughput: max is the
    one-sided-noise statistic, like the jobqueue band)."""
    from kubeflow_tpu.telemetry import slo
    from kubeflow_tpu.telemetry import fleetscrape as fs
    from kubeflow_tpu.telemetry.tsdb import TSDB

    les = ("0.01", "0.05", "0.2", "1.0", "5.0", "20.0", "60.0", "+Inf")

    def page(target: int, tick: int) -> str:
        base = (target * 131 + tick * 977) % 4096
        lines = [
            f"serve_queue_depth {base % 17}",
            "serve_decode_slots 8",
            f"serve_decode_slots_active {base % 9}",
            f'generate_requests_total{{outcome="ok"}} {base * 3}',
            f"serve_per_token_seconds_sum {base / 100.0}",
            f"serve_per_token_seconds_count {base}",
            f"process_cpu_seconds_total {tick * 2.5}",
            f"serve_input_tokens_total {base * 40}",
            f"serve_output_tokens_total {base * 11}",
            f"serve_batch_rows_sum {base}",
            f"serve_batch_rows_count {max(base // 4, 1)}",
        ]
        for i, le in enumerate(les):
            lines.append(
                "serve_time_to_first_token_seconds_bucket"
                f'{{le="{le}"}} {base * (i + 1) // len(les)}')
        return "\n".join(lines) + "\n"

    tick_box = [0]
    samples = []
    for _attempt in range(3):
        tsdb = TSDB(capacity=max(passes + 2, 8),
                    max_series=max(n_targets * 32, 8192))
        scraper = fs.FleetScraper(
            tsdb, scraper=lambda url: page(int(url.rsplit("/", 2)[-2]),
                                           tick_box[0]))
        targets = [fs.Target(url=f"http://replica/{i}/metrics",
                             labels={"service": f"bench/svc-{i % 20}",
                                     "replica": f"r{i}"})
                   for i in range(n_targets)]
        engine = slo.RuleEngine(tsdb, [slo.BurnRateRule(
            name="bench-ttft", threshold=1.0, objective=0.99,
            metric="serve_time_to_first_token_seconds_bucket",
            fast_window_s=60.0, slow_window_s=600.0)])
        stored = 0
        evals = 0
        t0 = time.perf_counter()
        for p in range(passes):
            tick_box[0] += 1
            stats = scraper.scrape(targets, ts=1000.0 + p)
            assert stats.ok == n_targets, stats
            stored += stats.samples
            engine.evaluate(at=1000.0 + p)
            evals += 1
        elapsed = time.perf_counter() - t0
        samples.append({
            "samples": stored, "evals": evals,
            "elapsed_s": elapsed,
            "samples_per_s": stored / max(elapsed, 1e-9),
            "series": len(tsdb),
        })
    best = max(samples, key=lambda s: s["samples_per_s"])
    return {
        "targets": n_targets,
        "passes": passes,
        "samples": best["samples"],
        "series": best["series"],
        "rule_evals": best["evals"],
        "elapsed_s": round(best["elapsed_s"], 4),
        "samples_per_s": round(best["samples_per_s"], 1),
        "samples_per_s_all": [round(s["samples_per_s"], 1)
                              for s in samples],
    }


def run_inference_scale(n_services: int = INFERENCE_SERVICES,
                        *, timeout: float = 120.0) -> dict:
    """The InferenceService autoscale-converge bench (ISSUE 12):
    ``n_services`` services at 1 replica, one synthetic traffic wave
    (per-replica queue depth 16 against a target of 4 → every service's
    target-tracking desired width is its max, 4), then the drain back to
    the floor.  The controller runs its REAL loop — informer caches,
    scrape → parse → decide → Deployment write → status — against
    FakeKube, with InferenceFleetSim playing the kubelet; only the
    /metrics pages are synthetic."""
    from kubeflow_tpu.platform.controllers import (
        inferenceservice as svcctrl,
    )
    from kubeflow_tpu.platform.k8s.types import INFERENCESERVICE
    from kubeflow_tpu.platform.testing import FakeKube
    from kubeflow_tpu.platform.testing.servesim import InferenceFleetSim

    ns = "serve-bench"
    kube = FakeKube()
    kube.add_namespace(ns)
    traffic = {"queue_depth": 0.0}

    def scraper(url):
        if url.endswith("/readyz"):
            return '{"ready": true}'
        return (f"serve_queue_depth {traffic['queue_depth']}\n"
                'generate_requests_total{outcome="ok"} 100\n')

    sim = InferenceFleetSim(
        kube, ns, endpoint_for=lambda svc, rev, i: f"sim://{svc}/{rev}/{i}")
    ctrl = svcctrl.make_controller(kube, scraper=scraper, sync_period=0.1)
    ctrl.workers = 8
    ctrl.start(kube)

    def all_at(target):
        services = kube.list(INFERENCESERVICE, ns)
        if len(services) < n_services:
            return False
        return all(
            (s.get("status") or {}).get("replicas") == target
            and (s.get("status") or {}).get("readyReplicas") == target
            for s in services)

    def wait_all(target, what):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if all_at(target):
                return
            time.sleep(0.02)
        raise TimeoutError(
            f"inference scale bench: {what} unconverged after {timeout}s")

    try:
        for i in range(n_services):
            kube.create({
                "apiVersion": "kubeflow.org/v1alpha1",
                "kind": "InferenceService",
                "metadata": {"name": f"svc-{i:03d}", "namespace": ns},
                "spec": {
                    "model": "llama_125m",
                    "tpu": {"accelerator": "v5e", "topology": "2x4"},
                    "replicas": {"min": 1, "max": 4, "initial": 1},
                    "scale": {"queueDepthTarget": 4.0,
                              "cooldownSeconds": 0.05},
                },
            })
        wait_all(1, "baseline 1-replica fleet")
        traffic["queue_depth"] = 16.0
        t0 = time.perf_counter()
        wait_all(4, "traffic-wave scale-up")
        up_s = time.perf_counter() - t0
        # Segment breakdown of the scale-up leg from the last service's
        # causal journey (same contract as the wave-converge line).
        segments = _journey_segments(
            kube, INFERENCESERVICE, f"svc-{n_services - 1:03d}", ns) or {}
        traffic["queue_depth"] = 0.0
        t1 = time.perf_counter()
        wait_all(1, "drain scale-down")
        down_s = time.perf_counter() - t1
        dead_letters = len(ctrl.dead_letters)
    finally:
        ctrl.stop()
        sim.close()
    return {
        "services": n_services,
        "wave_converge_s": round(up_s, 3),
        "drain_converge_s": round(down_s, 3),
        "converge_s": round(max(up_s, down_s), 3),
        "dead_letters": dead_letters,
        "segments": segments,
    }


def run_worker_sweep(n: int, *, workers=WORKER_SWEEP_WORKERS,
                     rtt_s: float = WORKER_SWEEP_RTT_S,
                     timeout: float = 300.0) -> dict:
    """Wave-converge-vs-workers: the SAME N-notebook wave on the same
    machine, one harness per worker count, every apiserver call of the
    controller carrying an injected ``rtt_s`` round-trip (a pure-latency
    ChaosKube schedule).  Parallel dispatch exists to overlap exactly
    this blocking time; the injected sleep releases the GIL the way a
    real socket wait does, while the in-process HTTP transport is
    GIL-bound end to end (client and server share one interpreter) and
    would measure CPU contention, not dispatch — that's what the separate
    wire-converge band is for.  Returns {workers: wave_dict}."""
    from kubeflow_tpu.platform.testing.chaos import Fault

    faults = [Fault("latency", 1.0, latency_s=rtt_s)]
    results = {}
    for w in workers:
        h = FleetHarness(workers=w, chaos_faults=faults)
        try:
            results[w] = h.wave(n, timeout=timeout)
        finally:
            h.close()
    return results


def run_wire_converge(n: int, *, workers: int = 4,
                      timeout: float = 300.0) -> dict:
    """Wire-level converge: the full controller + informer + watch stack
    over the real REST client against the fake served over HTTP
    (HttpKube), QPS limiter off so the band tracks the wire path itself
    (serialization, connection pool, chunked watch streams) rather than
    the client-side throttle."""
    import os

    saved = os.environ.get("K8S_CLIENT_QPS")
    os.environ["K8S_CLIENT_QPS"] = "0"
    try:
        h = FleetHarness(workers=workers, transport="http")
        try:
            return h.wave(n, timeout=timeout)
        finally:
            h.close()
    finally:
        if saved is None:
            del os.environ["K8S_CLIENT_QPS"]
        else:
            os.environ["K8S_CLIENT_QPS"] = saved


def _run_and_report_sharded(args) -> bool:
    """The two sharded-HA lines.  The converge band is valued only at
    the full 10k fleet (a smoke-size wave says nothing about scale) but
    both lines always self-report band fields so trending tooling never
    hits a gap; the per-replica load band is size-independent (the cache
    fraction is structural) and is asserted at any N."""
    sharded = run_sharded(args.sharded_fleet,
                          replicas=args.sharded_replicas)
    per_nb_ms = sharded["converge_s"] / max(sharded["fleet"], 1) * 1e3
    print(json.dumps({
        "metric": "ctrlplane_sharded_converge_s",
        "value": sharded["converge_s"],
        "unit": f"s ({sharded['fleet']}-notebook wave, "
                f"{sharded['replicas']} replicas x "
                f"{sharded['num_shards']} shards, lease TTL "
                f"{SHARDED_LEASE_S}s, memory transport)",
        "ms_per_notebook": round(per_nb_ms, 2),
        "fenced_writes_checked": sharded["fenced_writes_checked"],
        "shard_map": sharded["shard_map"],
        "vs_baseline": round(
            SHARDED_CONVERGE_BASELINE_S
            / max(sharded["converge_s"], 1e-9), 4),
        "band": "pass" if (
            sharded["converge_s"]
            <= SHARDED_CONVERGE_BASELINE_S * BAND_FACTOR
            and sharded["fenced_writes_checked"] > 0) else "REGRESSION",
        "band_floor": round(1.0 / BAND_FACTOR, 3),
    }), flush=True)
    load_ok = (sharded["replica_cache_frac_max"] <= SHARDED_CACHE_FRAC_MAX
               and sharded["replica_admit_frac_mean"] < 1.0)
    print(json.dumps({
        "metric": "ctrlplane_sharded_replica_load",
        "value": sharded["replica_cache_frac_max"],
        "unit": "max per-replica informer cache / single-process "
                "full-keyspace cache (lower = better scale-out)",
        "full_keyspace_objs": sharded["full_keyspace_objs"],
        "replica_cache_objs": sharded["replica_cache_objs"],
        "replica_events_seen": sharded["replica_events_seen"],
        "replica_events_admitted": sharded["replica_events_admitted"],
        "replica_admit_frac_mean": sharded["replica_admit_frac_mean"],
        "band": "pass" if load_ok else "REGRESSION",
        "band_floor": SHARDED_CACHE_FRAC_MAX,
    }), flush=True)
    # Server-side shard filtering (ISSUE 18): the banded value is the
    # MEAN per-replica decoded fraction of the informer-kind stream —
    # the per-replica max rides along unbanded because at smoke-size
    # waves the shard hash lottery swings single replicas well past the
    # mean on identical code.  < 1.0 is the structural assertion (every
    # replica decoded everything before server-side filtering);
    # DECODE_FRACTION_MAX bounds the steady-state slop.
    frac_ok = (sharded["decode_fraction_mean"] <= DECODE_FRACTION_MAX
               and sharded["decode_fraction_mean"] < 1.0
               and sharded["events_emitted_delta"] > 0)
    print(json.dumps({
        "metric": "ctrlplane_replica_decode_fraction",
        "value": sharded["decode_fraction_mean"],
        "unit": "mean per-replica fraction of emitted informer-kind "
                "events decoded (server-side shard filtering; 1.0 = "
                "every replica decodes the full stream)",
        "replica_decode_fraction": sharded["replica_decode_fraction"],
        "decode_fraction_max": sharded["decode_fraction_max"],
        "events_emitted_delta": sharded["events_emitted_delta"],
        "replicas": sharded["replicas"],
        "band": "pass" if frac_ok else "REGRESSION",
        "band_floor": DECODE_FRACTION_MAX,
    }), flush=True)
    converge_ok = (sharded["converge_s"]
                   <= SHARDED_CONVERGE_BASELINE_S * BAND_FACTOR
                   if sharded["fleet"] >= 1000 else True)
    # Zero fenced writes = the bench silently stopped exercising the
    # fence; that must fail the PROCESS (the ha-chaos lane gates on exit
    # code), not just color a band string.
    fence_ok = sharded["fenced_writes_checked"] > 0
    return load_ok and converge_ok and fence_ok and frac_ok


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--small", type=int, default=150)
    p.add_argument("--large", type=int, default=600)
    p.add_argument("--chaos-fleet", type=int, default=80)
    p.add_argument("--sweep-fleet", type=int, default=80,
                   help="wave size for the workers sweep (memory transport "
                        "+ injected per-call RTT) and the wire-converge "
                        "band (http transport)")
    p.add_argument("--churn-seconds", type=float, default=3.0)
    p.add_argument("--sharded-fleet", type=int, default=10_000,
                   help="wave size for the sharded-HA band (ISSUE 9: "
                        "10k objects across --sharded-replicas simulated "
                        "replicas)")
    p.add_argument("--sharded-replicas", type=int, default=SHARDED_REPLICAS)
    p.add_argument("--jobqueue-jobs", type=int, default=JOBQUEUE_JOBS,
                   help="pending-TPUJob count for the admission-decision "
                        "throughput band (ISSUE 11)")
    p.add_argument("--inference-services", type=int,
                   default=INFERENCE_SERVICES,
                   help="InferenceService count for the autoscale-"
                        "converge band (ISSUE 12: one traffic wave, "
                        "every service must reach its target width)")
    p.add_argument("--fleetscrape-targets", type=int,
                   default=FLEETSCRAPE_TARGETS,
                   help="synthetic scrape-target count for the fleet "
                        "metrics pipeline band (ISSUE 15: scrape -> "
                        "TSDB store -> burn-rate rule eval per pass)")
    p.add_argument("--profile-fleet", type=int, default=PROFILE_FLEET,
                   help="wave size for the profiler-overhead A/B band "
                        "(ISSUE 16: sampler on vs off, band "
                        f"<= {PROFILE_OVERHEAD_BAND_PCT:g}%%)")
    p.add_argument("--sharded-only", action="store_true",
                   help="run ONLY the sharded-HA phase (the ha-chaos "
                        "lane's 4-replica smoke)")
    p.add_argument("--transport", choices=["memory", "http"],
                   default="memory",
                   help="http = real REST client against the fake served "
                        "over the wire (BASELINE.md wire numbers; NOTE "
                        "the client QPS limiter dominates at default "
                        "K8S_CLIENT_QPS — set it to 0 to measure the "
                        "wire itself)")
    p.add_argument("--watch-window", type=float, default=None,
                   help="http transport: shrink the client's bounded "
                        "watch windows (resume-path stress)")
    args = p.parse_args(argv)

    if args.sharded_only:
        ok = _run_and_report_sharded(args)
        return 0 if ok else 1

    # Wire-codec decode A/B first: cheap, self-contained, and its corpus
    # generation warms nothing the fleet phases depend on.
    decode = run_decode_ab()
    decode_ok = (decode["native_eps"] >= DECODE_EPS_BASELINE / BAND_FACTOR
                 and decode["speedup_x"] >= DECODE_SPEEDUP_MIN)
    print(json.dumps({
        "metric": "ctrlplane_events_decoded_per_s",
        "value": decode["native_eps"],
        "unit": f"events/sec (native codec leg, {decode['events']} "
                f"realistic ~{decode['avg_line_bytes']:.0f}B pod watch "
                "lines, decode + 3 identity reads each, best of 3; "
                f"gate: native >= {DECODE_SPEEDUP_MIN:g}x python)",
        "python_eps": decode["python_eps"],
        "speedup_x": decode["speedup_x"],
        "avg_line_bytes": decode["avg_line_bytes"],
        "native_available": decode["native_available"],
        "native_load_error": decode["native_load_error"],
        "vs_baseline": round(
            decode["native_eps"] / DECODE_EPS_BASELINE, 4),
        "band": "pass" if decode_ok else "REGRESSION",
        "band_floor": round(1.0 / BAND_FACTOR, 3),
    }), flush=True)

    small = run_fleet(args.small, churn_s=args.churn_seconds,
                      transport=args.transport,
                      watch_window=args.watch_window, detail=False)
    large = run_fleet(args.large, churn_s=args.churn_seconds,
                      transport=args.transport,
                      watch_window=args.watch_window)

    per_nb_small = small["wave"]["converge_s"] / args.small * 1e3
    per_nb_large = large["wave"]["converge_s"] / args.large * 1e3
    scale_ratio = per_nb_large / per_nb_small
    resync_cpu = large["resync"]["cpu_s"]
    # The value baselines/bands belong to the memory transport only: over
    # http the numbers are wire- or QPS-limiter-bound by design
    # (BASELINE.md "Over the REAL wire") and would read as false
    # regressions against the in-memory constants.
    banded = args.transport == "memory"

    line = {
        "metric": "ctrlplane_fleet_converge_ms_per_notebook",
        "value": round(per_nb_large, 2), "unit": "ms/notebook",
        "fleet": args.large,
        "transport": args.transport,
        "converge_s": round(large["wave"]["converge_s"], 2),
        "peak_queue_depth": large["wave"]["peak_queue_depth"],
        "reconciles": large["wave"]["reconciles"],
        "reconcile_errors": large["wave"]["errors"],
        # Histogram-derived control-plane latency (the new
        # controller_runtime_reconcile_time_seconds series) — BENCH jsons
        # track where reconcile time goes, not just wave wall time.
        "reconcile_p50_ms": large["wave"]["reconcile_p50_ms"],
        "reconcile_p99_ms": large["wave"]["reconcile_p99_ms"],
        # Critical-path segment breakdown of the last notebook's journey
        # (telemetry/critical_path.py; docs/observability.md "Object
        # journeys"): where the ms/notebook actually goes.
        "converge_segments": large["wave"].get("segments") or {},
        "rss_mb_after": large["rss_mb_after"],
    }
    if banded:
        line.update({
            "vs_baseline": round(
                BASELINE["fleet_converge_ms_per_notebook"] / per_nb_large, 4),
            "band": _band(per_nb_large,
                          BASELINE["fleet_converge_ms_per_notebook"]),
            "band_floor": round(1.0 / BAND_FACTOR, 3),
        })
    print(json.dumps(line), flush=True)
    print(json.dumps({
        "metric": "ctrlplane_fleet_scale_ratio",
        "value": round(scale_ratio, 3), "unit": "x (per-notebook, "
        f"{args.large} vs {args.small} fleet)",
        "small_ms_per_notebook": round(per_nb_small, 2),
        "large_ms_per_notebook": round(per_nb_large, 2),
        "band": "pass" if scale_ratio <= SCALE_BAND else "REGRESSION",
        "band_floor": SCALE_BAND,
    }), flush=True)
    line = {
        "metric": "ctrlplane_fleet_resync_cpu_s",
        "value": round(resync_cpu, 3), "unit": "s (process CPU, "
        f"{large['resync']['n']}-object resync cycle, min of 3)",
        "transport": args.transport,
        "wall_s": round(large["resync"]["wall_s"], 3),
        "cycles_cpu_s": large["resync"]["cycles_cpu_s"],
    }
    if banded:
        line.update({
            "vs_baseline": round(
                BASELINE["fleet_resync_cpu_s"] / resync_cpu, 4)
            if resync_cpu else 1.0,
            "band": _band(resync_cpu, BASELINE["fleet_resync_cpu_s"]),
            "band_floor": round(1.0 / BAND_FACTOR, 3),
        })
    print(json.dumps(line), flush=True)
    # Read-path microbench (zero-copy frozen views): cached-read
    # throughput and the resync cycle's allocation footprint.  Banded on
    # the memory transport only, like the other value baselines.
    line = {
        "metric": "ctrlplane_cached_reads_per_s",
        "value": round(large["reads"]["get_per_s"], 0), "unit": "gets/sec "
        f"(informer store of {large['resync']['n']} objects)",
        "list_objs_per_s": round(large["reads"]["list_objs_per_s"], 0),
        "transport": args.transport,
    }
    if banded:
        line.update({
            "vs_baseline": round(
                large["reads"]["get_per_s"]
                / BASELINE["cached_get_per_s"], 4),
            "band": _band_min(large["reads"]["get_per_s"],
                              BASELINE["cached_get_per_s"]),
            "band_floor": round(1.0 / BAND_FACTOR, 3),
        })
    print(json.dumps(line), flush=True)
    line = {
        "metric": "ctrlplane_resync_alloc_peak_kb_per_obj",
        "value": round(large["alloc"]["peak_kb_per_obj"], 3),
        "unit": "KiB/object (tracemalloc peak over one resync cycle)",
        "peak_kb": round(large["alloc"]["peak_kb"], 1),
        "net_blocks": large["alloc"]["net_blocks"],
        "transport": args.transport,
    }
    if banded:
        line.update({
            "vs_baseline": round(
                BASELINE["resync_alloc_peak_kb_per_obj"]
                / max(large["alloc"]["peak_kb_per_obj"], 1e-9), 4),
            "band": _band(large["alloc"]["peak_kb_per_obj"],
                          BASELINE["resync_alloc_peak_kb_per_obj"]),
            "band_floor": round(1.0 / BAND_FACTOR, 3),
        })
    print(json.dumps(line), flush=True)
    chaos = run_chaos(args.chaos_fleet, transport=args.transport)
    line = {
        "metric": "ctrlplane_chaos_converge_s",
        "value": chaos["storm_converge_s"], "unit": "s (seeded storm, "
        f"{args.chaos_fleet}-notebook wave, rate {CHAOS_RATE}, "
        f"seed {CHAOS_SEED})",
        "clean_converge_s": chaos["clean_converge_s"],
        "storm_samples_s": chaos["storm_samples_s"],
        "overhead_x": chaos["overhead_x"],
        "faults_injected": chaos["faults_injected"],
        "dead_letters": chaos["dead_letters"],
        "reconcile_errors": chaos["reconcile_errors"],
        "transport": args.transport,
    }
    if banded:
        line.update({
            "vs_baseline": round(
                CHAOS_CONVERGE_BASELINE_S
                / max(chaos["storm_converge_s"], 1e-9), 4),
            "band": "pass" if (
                chaos["storm_converge_s"]
                <= CHAOS_CONVERGE_BASELINE_S * BAND_FACTOR
                and chaos["dead_letters"] == 0) else "REGRESSION",
            "band_floor": round(1.0 / BAND_FACTOR, 3),
        })
    print(json.dumps(line), flush=True)
    # Parallel dispatch proof (ISSUE 5): the workers sweep (injected-RTT
    # model, where overlap is what's being measured) and the wire-level
    # converge band (HttpKube, the full stack over a real socket).  Both
    # are transport-fixed so they stay meaningful whatever --transport
    # the rest of the run used.
    sweep = run_worker_sweep(args.sweep_fleet)
    w_lo, w_hi = min(sweep), max(sweep)
    lo_s, hi_s = sweep[w_lo]["converge_s"], sweep[w_hi]["converge_s"]
    speedup = lo_s / max(hi_s, 1e-9)
    print(json.dumps({
        "metric": "ctrlplane_wave_converge_workers",
        "value": round(speedup, 3),
        "unit": f"x speedup (workers={w_hi} vs workers={w_lo}, "
                f"{args.sweep_fleet}-notebook wave, "
                f"{WORKER_SWEEP_RTT_S * 1e3:g}ms injected RTT per call)",
        **{f"workers_{w}_converge_s": round(r["converge_s"], 3)
           for w, r in sorted(sweep.items())},
        **{f"workers_{w}_reconciles": r["reconciles"]
           for w, r in sorted(sweep.items())},
        "band": "pass" if speedup >= WORKER_SWEEP_MIN_SPEEDUP
        else "REGRESSION",
        "band_floor": WORKER_SWEEP_MIN_SPEEDUP,
    }), flush=True)
    jobq = run_jobqueue(args.jobqueue_jobs)
    print(json.dumps({
        "metric": "tpujob_queue_decisions_per_s",
        "value": jobq["decisions_per_s"],
        "unit": f"decisions/sec (drain of {jobq['n_jobs']} pending "
                f"TPUJobs across {jobq['profiles']} profiles, "
                "capacity-limited pool + per-profile quotas, best of 3)",
        "decisions": jobq["decisions"],
        "drain_s": jobq["drain_s"],
        "fill_s": jobq["fill_s"],
        "samples_per_s": jobq["samples_per_s"],
        "vs_baseline": round(
            jobq["decisions_per_s"] / JOBQUEUE_DECISIONS_BASELINE, 4),
        "band": _band_min(jobq["decisions_per_s"],
                          JOBQUEUE_DECISIONS_BASELINE),
        "band_floor": round(1.0 / BAND_FACTOR, 3),
    }), flush=True)
    scrape = run_fleetscrape(args.fleetscrape_targets)
    print(json.dumps({
        "metric": "fleetscrape_samples_per_s",
        "value": scrape["samples_per_s"],
        "unit": f"samples/sec ({scrape['targets']} targets x "
                f"{scrape['passes']} passes through scrape -> TSDB "
                "store -> burn-rate rule eval, best of 3)",
        "samples": scrape["samples"],
        "series": scrape["series"],
        "rule_evals": scrape["rule_evals"],
        "elapsed_s": scrape["elapsed_s"],
        "samples_per_s_all": scrape["samples_per_s_all"],
        "vs_baseline": round(
            scrape["samples_per_s"] / FLEETSCRAPE_SAMPLES_BASELINE, 4),
        "band": _band_min(scrape["samples_per_s"],
                          FLEETSCRAPE_SAMPLES_BASELINE),
        "band_floor": round(1.0 / BAND_FACTOR, 3),
    }), flush=True)
    profile = run_profile_overhead(args.profile_fleet)
    print(json.dumps({
        "metric": "ctrlplane_profile_overhead_pct",
        "value": profile["overhead_pct"],
        "unit": f"% sampler CPU vs converge CPU "
                f"({args.profile_fleet}-notebook x {profile['waves']}-wave "
                "arms, default KFT_PROFILE_HZ; wall A/B legs ride as "
                "evidence)",
        "sampler_cpu_s": profile["sampler_cpu_s"],
        "converge_cpu_s": profile["converge_cpu_s"],
        "converge_off_s": profile["converge_off_s"],
        "converge_on_s": profile["converge_on_s"],
        "off_samples_s": profile["off_samples_s"],
        "on_samples_s": profile["on_samples_s"],
        "profile_samples": profile["profile_samples"],
        "roles": profile["roles"],
        "band": "pass" if (
            profile["overhead_pct"] <= PROFILE_OVERHEAD_BAND_PCT
            and profile["profile_samples"] > 0) else "REGRESSION",
        "band_floor": PROFILE_OVERHEAD_BAND_PCT,
    }), flush=True)
    inference = run_inference_scale(args.inference_services)
    inference_ok = (inference["dead_letters"] == 0
                    and (inference["converge_s"]
                         <= INFERENCE_SCALE_BASELINE_S * BAND_FACTOR
                         or args.inference_services < INFERENCE_SERVICES))
    print(json.dumps({
        "metric": "inferenceservice_scale_converge_s",
        "value": inference["converge_s"],
        "unit": f"s (worst leg of one traffic wave over "
                f"{inference['services']} services, 1->4->1 replicas, "
                "synthetic serve series through the real scrape path)",
        "wave_converge_s": inference["wave_converge_s"],
        "drain_converge_s": inference["drain_converge_s"],
        "services": inference["services"],
        "dead_letters": inference["dead_letters"],
        "converge_segments": inference.get("segments") or {},
        "vs_baseline": round(
            INFERENCE_SCALE_BASELINE_S
            / max(inference["converge_s"], 1e-9), 4),
        "band": "pass" if inference_ok else "REGRESSION",
        "band_floor": round(1.0 / BAND_FACTOR, 3),
    }), flush=True)
    wire = run_wire_converge(args.sweep_fleet)
    print(json.dumps({
        "metric": "ctrlplane_wire_converge_s",
        "value": round(wire["converge_s"], 3),
        "unit": f"s ({args.sweep_fleet}-notebook wave, http transport, "
                "workers=4, QPS limiter off)",
        "reconcile_errors": wire["errors"],
        "reconciles": wire["reconciles"],
        "cpu_s": round(wire["cpu_s"], 3),
        "vs_baseline": round(
            WIRE_CONVERGE_BASELINE_S / max(wire["converge_s"], 1e-9), 4),
        "band": _band(wire["converge_s"], WIRE_CONVERGE_BASELINE_S),
        "band_floor": round(1.0 / BAND_FACTOR, 3),
    }), flush=True)
    _run_and_report_sharded(args)
    print(json.dumps({
        "metric": "ctrlplane_fleet_churn",
        "value": round(large["churn"]["achieved_hz"], 1), "unit": "updates/sec",
        "p95_queue_depth": large["churn"]["p95_queue_depth"],
        "drained": large["churn"]["drained"],
        "new_errors": large["churn"]["new_errors"],
        "band": "pass" if (large["churn"]["drained"]
                           and large["churn"]["new_errors"] == 0)
        else "REGRESSION",
    }), flush=True)
    ok = (scale_ratio <= SCALE_BAND and large["churn"]["drained"]
          and inference["dead_letters"] == 0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
