// Sequence packer: best-fit-decreasing bin packing of variable-length
// documents into fixed-length training rows.
//
// Role: the TPU build's LM data pipeline packs documents into [rows,
// seq_len] token matrices with segment ids so no FLOPs are spent on
// padding (the reference platform has no data pipeline at all — SURVEY.md
// §2.13).  Packing is a host-side hot path (per input shard, every
// epoch), hence native, mirroring how this repo's other control-plane hot
// paths (jsonpatch.cc, workqueue.cc) are C++ with Python fallbacks.
//
// Algorithm: best-fit decreasing — sort documents by length descending,
// place each into the open row with the smallest remaining capacity that
// still fits (multiset lower_bound, O(n log n)), else open a new row.
// Classical guarantee: <= 11/9 OPT + 4 rows.
//
// C ABI (ctypes):
//   int64 kfpk_pack(const int64* lengths, int64 n, int64 row_len,
//                   int64* row_assignment, int64* row_offset)
// Returns the number of rows used, or -1 if any length is < 1 or
// > row_len.  row_assignment[i] = row of doc i; row_offset[i] = first
// slot of doc i within its row.

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <set>
#include <utility>
#include <vector>

extern "C" {

int64_t kfpk_pack(const int64_t* lengths, int64_t n, int64_t row_len,
                  int64_t* row_assignment, int64_t* row_offset) {
  if (n < 0 || row_len < 1) return -1;
  for (int64_t i = 0; i < n; ++i) {
    if (lengths[i] < 1 || lengths[i] > row_len) return -1;
  }
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return lengths[a] > lengths[b];
  });

  // (remaining_capacity, row_id); lower_bound finds the tightest fit.
  std::multiset<std::pair<int64_t, int64_t>> open;
  std::vector<int64_t> used;  // used[r] = filled slots in row r
  for (int64_t idx : order) {
    const int64_t len = lengths[idx];
    auto it = open.lower_bound({len, -1});
    int64_t row;
    if (it == open.end()) {
      row = static_cast<int64_t>(used.size());
      used.push_back(0);
    } else {
      row = it->second;
      open.erase(it);
    }
    row_assignment[idx] = row;
    row_offset[idx] = used[row];
    used[row] += len;
    const int64_t rem = row_len - used[row];
    if (rem > 0) open.insert({rem, row});
  }
  return static_cast<int64_t>(used.size());
}

}  // extern "C"
