// kfnative: native control-plane hot paths for the kubeflow_tpu platform.
//
// Two subsystems, one shared library (libkfnative.so):
//
//   kfp_*  — JSON parse/serialize + RFC 6902 patch create/apply.  This is the
//            admission-webhook hot path: every pod created in a profile
//            namespace is diffed (pod-before vs pod-after PodDefault merge)
//            into a JSONPatch for the AdmissionReview response.  Semantics
//            mirror kubeflow_tpu/platform/webhook/jsonpatch.py exactly (the
//            reference webhook computes the same patch with a Go library,
//            reference admission-webhook/main.go:683-695).
//
//   kfq_*  — delaying, rate-limited, deduplicating workqueue (see workqueue.cc)
//            mirroring kubeflow_tpu/platform/runtime/controller.py::_WorkQueue
//            (the reference's controller-runtime workqueue is Go,
//            client-go util/workqueue).
//
// C API only (loaded via ctypes — pybind11 is not available in this image).
// Returned strings are heap-allocated; callers free with kfp_free().

#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <utility>
#include <vector>

namespace kf {

// ---------------------------------------------------------------------------
// JSON value model.  Objects preserve insertion order (patch output ordering
// matches the Python implementation, which iterates dicts in insertion order).
// ---------------------------------------------------------------------------

struct Value;
using ValuePtr = std::shared_ptr<Value>;

// Big: an integer outside int64 range, kept as its source token so values
// like 2**63+1 round-trip exactly (Python ints are arbitrary precision; the
// diff must see them change).
enum class Kind : uint8_t { Null, Bool, Int, Double, Str, Arr, Obj, Big };

struct Value {
  Kind kind = Kind::Null;
  bool b = false;
  int64_t i = 0;
  double d = 0;
  std::string s;
  std::vector<ValuePtr> arr;
  std::vector<std::pair<std::string, ValuePtr>> obj;

  static ValuePtr null() { return std::make_shared<Value>(); }
  static ValuePtr boolean(bool v) {
    auto p = std::make_shared<Value>();
    p->kind = Kind::Bool;
    p->b = v;
    return p;
  }
  static ValuePtr integer(int64_t v) {
    auto p = std::make_shared<Value>();
    p->kind = Kind::Int;
    p->i = v;
    return p;
  }
  static ValuePtr real(double v) {
    auto p = std::make_shared<Value>();
    p->kind = Kind::Double;
    p->d = v;
    return p;
  }
  static ValuePtr str(std::string v) {
    auto p = std::make_shared<Value>();
    p->kind = Kind::Str;
    p->s = std::move(v);
    return p;
  }
  static ValuePtr big(std::string tok) {
    auto p = std::make_shared<Value>();
    p->kind = Kind::Big;
    p->s = std::move(tok);
    return p;
  }
  static ValuePtr array() {
    auto p = std::make_shared<Value>();
    p->kind = Kind::Arr;
    return p;
  }
  static ValuePtr object() {
    auto p = std::make_shared<Value>();
    p->kind = Kind::Obj;
    return p;
  }

  ValuePtr* find(const std::string& key) {
    for (auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
  void set(const std::string& key, ValuePtr v) {
    if (auto* p = find(key)) {
      *p = std::move(v);
      return;
    }
    obj.emplace_back(key, std::move(v));
  }
  bool erase(const std::string& key) {
    for (auto it = obj.begin(); it != obj.end(); ++it)
      if (it->first == key) {
        obj.erase(it);
        return true;
      }
    return false;
  }
};

// Deep *value* equality with Python == semantics: bools are numeric
// (True == 1), int/float compare numerically, big ints compare by token
// against int64 and numerically against doubles.  This is the comparison
// the Python diff performs via `before[key] != after[key]`, so the native
// and Python engines emit identical patches (tests/ctrlplane/test_native.py).
static bool equal(const Value& a, const Value& b) {
  if (a.kind != b.kind) {
    if (a.kind == Kind::Big || b.kind == Kind::Big) {
      const Value& big = a.kind == Kind::Big ? a : b;
      const Value& other = a.kind == Kind::Big ? b : a;
      if (other.kind == Kind::Int) return std::to_string(other.i) == big.s;
      if (other.kind == Kind::Double)
        return std::strtod(big.s.c_str(), nullptr) == other.d;
      if (other.kind == Kind::Bool) return false;  // magnitude rules it out
      return false;
    }
    auto num = [](const Value& v, double* out) {
      if (v.kind == Kind::Int) {
        *out = static_cast<double>(v.i);
        return true;
      }
      if (v.kind == Kind::Double) {
        *out = v.d;
        return true;
      }
      if (v.kind == Kind::Bool) {
        *out = v.b ? 1.0 : 0.0;
        return true;
      }
      return false;
    };
    double x, y;
    if (num(a, &x) && num(b, &y)) return x == y;
    return false;
  }
  switch (a.kind) {
    case Kind::Big:
      return a.s == b.s;
    case Kind::Null:
      return true;
    case Kind::Bool:
      return a.b == b.b;
    case Kind::Int:
      return a.i == b.i;
    case Kind::Double:
      return a.d == b.d;
    case Kind::Str:
      return a.s == b.s;
    case Kind::Arr: {
      if (a.arr.size() != b.arr.size()) return false;
      for (size_t k = 0; k < a.arr.size(); ++k)
        if (!equal(*a.arr[k], *b.arr[k])) return false;
      return true;
    }
    case Kind::Obj: {
      if (a.obj.size() != b.obj.size()) return false;
      // Key order does not affect equality.
      for (auto& kv : a.obj) {
        bool found = false;
        for (auto& kv2 : b.obj)
          if (kv2.first == kv.first) {
            if (!equal(*kv.second, *kv2.second)) return false;
            found = true;
            break;
          }
        if (!found) return false;
      }
      return true;
    }
  }
  return false;
}

static ValuePtr deep_copy(const Value& v) {
  auto p = std::make_shared<Value>();
  p->kind = v.kind;
  p->b = v.b;
  p->i = v.i;
  p->d = v.d;
  p->s = v.s;
  for (auto& e : v.arr) p->arr.push_back(deep_copy(*e));
  for (auto& kv : v.obj) p->obj.emplace_back(kv.first, deep_copy(*kv.second));
  return p;
}

// ---------------------------------------------------------------------------
// Parser (strict JSON, UTF-8 passthrough).
// ---------------------------------------------------------------------------

struct ParseError {
  std::string msg;
};

class Parser {
 public:
  explicit Parser(const char* text) : p_(text) {}

  ValuePtr parse() {
    skip_ws();
    ValuePtr v = parse_value();
    skip_ws();
    if (*p_ != '\0') throw ParseError{"trailing characters"};
    return v;
  }

 private:
  const char* p_;

  void skip_ws() {
    while (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' || *p_ == '\r') ++p_;
  }

  [[noreturn]] void fail(const std::string& what) { throw ParseError{what}; }

  bool consume(const char* lit) {
    size_t n = std::strlen(lit);
    if (std::strncmp(p_, lit, n) == 0) {
      p_ += n;
      return true;
    }
    return false;
  }

  ValuePtr parse_value() {
    switch (*p_) {
      case 'n':
        if (consume("null")) return Value::null();
        fail("bad literal");
      case 't':
        if (consume("true")) return Value::boolean(true);
        fail("bad literal");
      case 'f':
        if (consume("false")) return Value::boolean(false);
        fail("bad literal");
      case '"':
        return Value::str(parse_string());
      case '[':
        return parse_array();
      case '{':
        return parse_object();
      default:
        return parse_number();
    }
  }

  std::string parse_string() {
    if (*p_ != '"') fail("expected string");
    ++p_;
    std::string out;
    while (*p_ != '"') {
      if (*p_ == '\0') fail("unterminated string");
      if (*p_ == '\\') {
        ++p_;
        switch (*p_) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned cp = 0;
            for (int k = 0; k < 4; ++k) {
              ++p_;
              char c = *p_;
              cp <<= 4;
              if (c >= '0' && c <= '9')
                cp |= c - '0';
              else if (c >= 'a' && c <= 'f')
                cp |= c - 'a' + 10;
              else if (c >= 'A' && c <= 'F')
                cp |= c - 'A' + 10;
              else
                fail("bad \\u escape");
            }
            // Surrogate pair?
            if (cp >= 0xD800 && cp <= 0xDBFF && p_[1] == '\\' && p_[2] == 'u') {
              unsigned lo = 0;
              const char* q = p_ + 3;
              for (int k = 0; k < 4; ++k) {
                char c = q[k];
                lo <<= 4;
                if (c >= '0' && c <= '9')
                  lo |= c - '0';
                else if (c >= 'a' && c <= 'f')
                  lo |= c - 'a' + 10;
                else if (c >= 'A' && c <= 'F')
                  lo |= c - 'A' + 10;
                else
                  fail("bad \\u escape");
              }
              if (lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                p_ = q + 3;  // past the 4 hex digits (loop ++ consumes last)
              }
            }
            // Encode UTF-8.
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xF0 | (cp >> 18));
              out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            fail("bad escape");
        }
        ++p_;
      } else {
        out += *p_++;
      }
    }
    ++p_;
    return out;
  }

  ValuePtr parse_number() {
    const char* start = p_;
    if (*p_ == '-') ++p_;
    while (*p_ >= '0' && *p_ <= '9') ++p_;
    bool is_double = false;
    if (*p_ == '.') {
      is_double = true;
      ++p_;
      while (*p_ >= '0' && *p_ <= '9') ++p_;
    }
    if (*p_ == 'e' || *p_ == 'E') {
      is_double = true;
      ++p_;
      if (*p_ == '+' || *p_ == '-') ++p_;
      while (*p_ >= '0' && *p_ <= '9') ++p_;
    }
    if (p_ == start || (p_ == start + 1 && *start == '-')) fail("bad number");
    std::string tok(start, p_);
    if (!is_double) {
      errno = 0;
      char* end = nullptr;
      long long v = std::strtoll(tok.c_str(), &end, 10);
      if (errno == 0 && end && *end == '\0') return Value::integer(v);
      return Value::big(tok);  // out-of-int64-range integer: keep the token
    }
    return Value::real(std::strtod(tok.c_str(), nullptr));
  }

  ValuePtr parse_array() {
    ++p_;  // [
    auto v = Value::array();
    skip_ws();
    if (*p_ == ']') {
      ++p_;
      return v;
    }
    while (true) {
      skip_ws();
      v->arr.push_back(parse_value());
      skip_ws();
      if (*p_ == ',') {
        ++p_;
        continue;
      }
      if (*p_ == ']') {
        ++p_;
        return v;
      }
      fail("expected , or ] in array");
    }
  }

  ValuePtr parse_object() {
    ++p_;  // {
    auto v = Value::object();
    skip_ws();
    if (*p_ == '}') {
      ++p_;
      return v;
    }
    while (true) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      if (*p_ != ':') fail("expected : in object");
      ++p_;
      skip_ws();
      v->set(key, parse_value());
      skip_ws();
      if (*p_ == ',') {
        ++p_;
        continue;
      }
      if (*p_ == '}') {
        ++p_;
        return v;
      }
      fail("expected , or } in object");
    }
  }
};

// ---------------------------------------------------------------------------
// Serializer (compact; separators match Python json.dumps(..., separators=(",", ":"))
// so round-trips are byte-comparable in tests).
// ---------------------------------------------------------------------------

static void serialize(const Value& v, std::string& out) {
  switch (v.kind) {
    case Kind::Null:
      out += "null";
      break;
    case Kind::Bool:
      out += v.b ? "true" : "false";
      break;
    case Kind::Int: {
      out += std::to_string(v.i);
      break;
    }
    case Kind::Big: {
      out += v.s;
      break;
    }
    case Kind::Double: {
      if (std::isfinite(v.d)) {
        char buf[32];
        snprintf(buf, sizeof(buf), "%.17g", v.d);
        // Trim to shortest round-trip representation like Python repr.
        double parsed = std::strtod(buf, nullptr);
        for (int prec = 1; prec < 17; ++prec) {
          char buf2[32];
          snprintf(buf2, sizeof(buf2), "%.*g", prec, v.d);
          if (std::strtod(buf2, nullptr) == parsed) {
            std::memcpy(buf, buf2, sizeof(buf2));
            break;
          }
        }
        out += buf;
        if (!std::strpbrk(buf, ".eE")) out += ".0";
      } else {
        out += "null";  // JSON has no NaN/Inf
      }
      break;
    }
    case Kind::Str: {
      out += '"';
      for (unsigned char c : v.s) {
        switch (c) {
          case '"': out += "\\\""; break;
          case '\\': out += "\\\\"; break;
          case '\b': out += "\\b"; break;
          case '\f': out += "\\f"; break;
          case '\n': out += "\\n"; break;
          case '\r': out += "\\r"; break;
          case '\t': out += "\\t"; break;
          default:
            if (c < 0x20) {
              char buf[8];
              snprintf(buf, sizeof(buf), "\\u%04x", c);
              out += buf;
            } else {
              out += static_cast<char>(c);  // UTF-8 passthrough
            }
        }
      }
      out += '"';
      break;
    }
    case Kind::Arr: {
      out += '[';
      for (size_t k = 0; k < v.arr.size(); ++k) {
        if (k) out += ',';
        serialize(*v.arr[k], out);
      }
      out += ']';
      break;
    }
    case Kind::Obj: {
      out += '{';
      bool first = true;
      for (auto& kv : v.obj) {
        if (!first) out += ',';
        first = false;
        serialize(*Value::str(kv.first), out);
        out += ':';
        serialize(*kv.second, out);
      }
      out += '}';
      break;
    }
  }
}

// ---------------------------------------------------------------------------
// RFC 6901 pointers + RFC 6902 create_patch / apply_patch.
// ---------------------------------------------------------------------------

static std::string escape_token(const std::string& t) {
  std::string out;
  for (char c : t) {
    if (c == '~')
      out += "~0";
    else if (c == '/')
      out += "~1";
    else
      out += c;
  }
  return out;
}

static std::string unescape_token(const std::string& t) {
  std::string out;
  for (size_t k = 0; k < t.size(); ++k) {
    if (t[k] == '~' && k + 1 < t.size() && t[k + 1] == '1') {
      out += '/';
      ++k;
    } else if (t[k] == '~' && k + 1 < t.size() && t[k + 1] == '0') {
      out += '~';
      ++k;
    } else {
      out += t[k];
    }
  }
  return out;
}

struct PatchError {
  std::string msg;
};

static std::vector<std::string> split_pointer(const std::string& ptr) {
  if (ptr.empty() || ptr[0] != '/') throw PatchError{"invalid pointer " + ptr};
  std::vector<std::string> out;
  size_t start = 1;
  for (size_t k = 1; k <= ptr.size(); ++k) {
    if (k == ptr.size() || ptr[k] == '/') {
      out.push_back(unescape_token(ptr.substr(start, k - start)));
      start = k + 1;
    }
  }
  return out;
}

static long array_index(const std::string& tok) {
  if (tok.empty()) throw PatchError{"bad array index"};
  for (char c : tok)
    if (c < '0' || c > '9') {
      if (!(c == '-' && tok.size() > 1)) throw PatchError{"bad array index " + tok};
    }
  return std::strtol(tok.c_str(), nullptr, 10);
}

// Returns the parent container of the pointer target + last token.
static std::pair<ValuePtr, std::string> walk(ValuePtr doc, const std::string& ptr,
                                             bool create) {
  auto tokens = split_pointer(ptr);
  ValuePtr cur = doc;
  for (size_t k = 0; k + 1 < tokens.size(); ++k) {
    const std::string& tok = tokens[k];
    if (cur->kind == Kind::Arr) {
      long idx = array_index(tok);
      if (idx < 0 || static_cast<size_t>(idx) >= cur->arr.size())
        throw PatchError{"index out of range in " + ptr};
      cur = cur->arr[idx];
    } else if (cur->kind == Kind::Obj) {
      ValuePtr* next = cur->find(tok);
      if (!next && create) {
        cur->set(tok, Value::object());
        next = cur->find(tok);
      }
      if (!next) throw PatchError{"path " + ptr + ": missing " + tok};
      cur = *next;
    } else {
      throw PatchError{"path " + ptr + ": cannot traverse scalar"};
    }
  }
  return {cur, tokens.back()};
}

static ValuePtr pointer_get(ValuePtr doc, const std::string& ptr) {
  auto [parent, last] = walk(doc, ptr, false);
  if (parent->kind == Kind::Arr) {
    long idx = array_index(last);
    if (idx < 0 || static_cast<size_t>(idx) >= parent->arr.size())
      throw PatchError{"index out of range in " + ptr};
    return parent->arr[idx];
  }
  if (ValuePtr* p = parent->find(last)) return *p;
  return Value::null();
}

static void pointer_add(ValuePtr doc, const std::string& ptr, ValuePtr val) {
  auto [parent, last] = walk(doc, ptr, true);
  if (parent->kind == Kind::Arr) {
    if (last == "-") {
      parent->arr.push_back(std::move(val));
    } else {
      long idx = array_index(last);
      if (idx < 0 || static_cast<size_t>(idx) > parent->arr.size())
        throw PatchError{"index out of range in " + ptr};
      parent->arr.insert(parent->arr.begin() + idx, std::move(val));
    }
  } else if (parent->kind == Kind::Obj) {
    parent->set(last, std::move(val));
  } else {
    throw PatchError{"add into scalar at " + ptr};
  }
}

static ValuePtr apply_patch(ValuePtr doc, const Value& ops) {
  if (ops.kind != Kind::Arr) throw PatchError{"patch must be an array"};
  doc = deep_copy(*doc);
  for (auto& opv : ops.arr) {
    Value& op = *opv;
    if (op.kind != Kind::Obj) throw PatchError{"op must be an object"};
    ValuePtr* kindp = op.find("op");
    if (!kindp || (*kindp)->kind != Kind::Str) throw PatchError{"missing op"};
    const std::string& kind = (*kindp)->s;
    std::string path;
    if (ValuePtr* p = op.find("path")) path = (*p)->s;

    if ((kind == "add" || kind == "replace") && path.empty()) {
      ValuePtr* v = op.find("value");
      if (!v) throw PatchError{"missing value"};
      doc = deep_copy(**v);
      continue;
    }
    if (kind == "add") {
      ValuePtr* v = op.find("value");
      if (!v) throw PatchError{"missing value"};
      pointer_add(doc, path, deep_copy(**v));
    } else if (kind == "replace") {
      ValuePtr* v = op.find("value");
      if (!v) throw PatchError{"missing value"};
      auto [parent, last] = walk(doc, path, false);
      if (parent->kind == Kind::Arr) {
        long idx = array_index(last);
        if (idx < 0 || static_cast<size_t>(idx) >= parent->arr.size())
          throw PatchError{"index out of range in " + path};
        parent->arr[idx] = deep_copy(**v);
      } else {
        if (!parent->find(last)) throw PatchError{"replace at missing path " + path};
        parent->set(last, deep_copy(**v));
      }
    } else if (kind == "remove") {
      auto [parent, last] = walk(doc, path, false);
      if (parent->kind == Kind::Arr) {
        long idx = array_index(last);
        if (idx < 0 || static_cast<size_t>(idx) >= parent->arr.size())
          throw PatchError{"index out of range in " + path};
        parent->arr.erase(parent->arr.begin() + idx);
      } else {
        if (!parent->erase(last)) throw PatchError{"remove at missing path " + path};
      }
    } else if (kind == "test") {
      ValuePtr cur = pointer_get(doc, path);
      ValuePtr* v = op.find("value");
      ValuePtr expect = v ? *v : Value::null();
      if (!equal(*cur, *expect)) throw PatchError{"test failed at " + path};
    } else if (kind == "move" || kind == "copy") {
      ValuePtr* fromp = op.find("from");
      if (!fromp) throw PatchError{"missing from"};
      const std::string& from = (*fromp)->s;
      ValuePtr val = deep_copy(*pointer_get(doc, from));
      if (kind == "move") {
        auto [sp, sl] = walk(doc, from, false);
        if (sp->kind == Kind::Arr) {
          long idx = array_index(sl);
          sp->arr.erase(sp->arr.begin() + idx);
        } else {
          sp->erase(sl);
        }
      }
      pointer_add(doc, path, std::move(val));
    } else {
      throw PatchError{"unknown op " + kind};
    }
  }
  return doc;
}

// Distinct-type check for the diff, mirroring Python's `type(b) is not
// type(a)`: bool vs int differ, int vs float differ, but Int and Big are
// both Python ints.
static bool same_kind(const Value& a, const Value& b) {
  if (a.kind == b.kind) return true;
  auto is_int = [](const Value& v) {
    return v.kind == Kind::Int || v.kind == Kind::Big;
  };
  return is_int(a) && is_int(b);
}

static void create_patch(const Value& before, const Value& after,
                         const std::string& path, ValuePtr out) {
  if (!same_kind(before, after)) {
    auto op = Value::object();
    op->set("op", Value::str("replace"));
    op->set("path", Value::str(path));
    op->set("value", deep_copy(after));
    out->arr.push_back(op);
    return;
  }
  if (before.kind == Kind::Obj) {
    for (auto& kv : before.obj) {
      std::string sub = path + "/" + escape_token(kv.first);
      auto it = const_cast<Value&>(after).find(kv.first);
      if (!it) {
        auto op = Value::object();
        op->set("op", Value::str("remove"));
        op->set("path", Value::str(sub));
        out->arr.push_back(op);
      } else if (!equal(*kv.second, **it)) {
        create_patch(*kv.second, **it, sub, out);
      }
    }
    for (auto& kv : after.obj) {
      if (!const_cast<Value&>(before).find(kv.first)) {
        auto op = Value::object();
        op->set("op", Value::str("add"));
        op->set("path", Value::str(path + "/" + escape_token(kv.first)));
        op->set("value", deep_copy(*kv.second));
        out->arr.push_back(op);
      }
    }
    return;
  }
  if (!equal(before, after)) {
    auto op = Value::object();
    op->set("op", Value::str("replace"));
    op->set("path", Value::str(path));
    op->set("value", deep_copy(after));
    out->arr.push_back(op);
  }
}

// ---------------------------------------------------------------------------
// RFC 7386 JSON merge patch (apply + create).  FakeKube.patch and the REST
// client's merge-patch path use this; semantics mirror
// kubeflow_tpu/platform/testing/fake.py::_merge_patch.
// ---------------------------------------------------------------------------

static ValuePtr merge_patch_apply(const Value& target, const Value& patch) {
  if (patch.kind != Kind::Obj) return deep_copy(patch);
  ValuePtr result =
      target.kind == Kind::Obj ? deep_copy(target) : Value::object();
  for (const auto& kv : patch.obj) {
    if (kv.second->kind == Kind::Null) {
      result->erase(kv.first);
      continue;
    }
    ValuePtr* cur = result->find(kv.first);
    if (cur && (*cur)->kind == Kind::Obj && kv.second->kind == Kind::Obj) {
      result->set(kv.first, merge_patch_apply(**cur, *kv.second));
    } else {
      // RFC 7386: patching a non-object target applies the patch to {},
      // which also strips nulls nested inside the patch value.
      Value empty;
      result->set(kv.first, merge_patch_apply(empty, *kv.second));
    }
  }
  return result;
}

static ValuePtr merge_patch_create(const Value& before, const Value& after) {
  if (before.kind != Kind::Obj || after.kind != Kind::Obj)
    return deep_copy(after);
  auto patch = Value::object();
  for (const auto& kv : before.obj) {
    if (!const_cast<Value&>(after).find(kv.first))
      patch->set(kv.first, Value::null());
  }
  for (const auto& kv : after.obj) {
    ValuePtr* b = const_cast<Value&>(before).find(kv.first);
    if (!b) {
      patch->set(kv.first, deep_copy(*kv.second));
    } else if (!equal(**b, *kv.second)) {
      if ((*b)->kind == Kind::Obj && kv.second->kind == Kind::Obj)
        patch->set(kv.first, merge_patch_create(**b, *kv.second));
      else
        patch->set(kv.first, deep_copy(*kv.second));
    }
  }
  return patch;
}

}  // namespace kf

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

static thread_local std::string g_error;

static const char* dup_out(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

extern "C" {

const char* kfp_last_error() { return g_error.c_str(); }

void kfp_free(const char* p) { std::free(const_cast<char*>(p)); }

// Diff two JSON documents → RFC 6902 patch (JSON array), or NULL on error.
const char* kfp_create_patch(const char* before, const char* after) {
  try {
    kf::ValuePtr b = kf::Parser(before).parse();
    kf::ValuePtr a = kf::Parser(after).parse();
    auto out = kf::Value::array();
    kf::create_patch(*b, *a, "", out);
    std::string s;
    kf::serialize(*out, s);
    return dup_out(s);
  } catch (const kf::ParseError& e) {
    g_error = "parse error: " + e.msg;
  } catch (const kf::PatchError& e) {
    g_error = e.msg;
  } catch (...) {
    g_error = "unknown error";
  }
  return nullptr;
}

// Apply an RFC 6902 patch to a document → patched JSON, or NULL on error.
const char* kfp_apply_patch(const char* doc, const char* patch) {
  try {
    kf::ValuePtr d = kf::Parser(doc).parse();
    kf::ValuePtr p = kf::Parser(patch).parse();
    kf::ValuePtr out = kf::apply_patch(d, *p);
    std::string s;
    kf::serialize(*out, s);
    return dup_out(s);
  } catch (const kf::ParseError& e) {
    g_error = "parse error: " + e.msg;
  } catch (const kf::PatchError& e) {
    g_error = e.msg;
  } catch (...) {
    g_error = "unknown error";
  }
  return nullptr;
}

// RFC 7386: apply a merge patch to a document → merged JSON, or NULL.
const char* kfp_merge_apply(const char* doc, const char* patch) {
  try {
    kf::ValuePtr d = kf::Parser(doc).parse();
    kf::ValuePtr p = kf::Parser(patch).parse();
    kf::ValuePtr out = kf::merge_patch_apply(*d, *p);
    std::string s;
    kf::serialize(*out, s);
    return dup_out(s);
  } catch (const kf::ParseError& e) {
    g_error = "parse error: " + e.msg;
  } catch (...) {
    g_error = "unknown error";
  }
  return nullptr;
}

// RFC 7386: diff two documents → the merge patch turning before into after.
const char* kfp_merge_create(const char* before, const char* after) {
  try {
    kf::ValuePtr b = kf::Parser(before).parse();
    kf::ValuePtr a = kf::Parser(after).parse();
    kf::ValuePtr out = kf::merge_patch_create(*b, *a);
    std::string s;
    kf::serialize(*out, s);
    return dup_out(s);
  } catch (const kf::ParseError& e) {
    g_error = "parse error: " + e.msg;
  } catch (...) {
    g_error = "unknown error";
  }
  return nullptr;
}

// Round-trip canonicalization (parse + compact serialize); used by tests.
const char* kfp_canonical(const char* doc) {
  try {
    kf::ValuePtr d = kf::Parser(doc).parse();
    std::string s;
    kf::serialize(*d, s);
    return dup_out(s);
  } catch (const kf::ParseError& e) {
    g_error = "parse error: " + e.msg;
    return nullptr;
  }
}

}  // extern "C"
