// wirecodec.cc — structural scanner for the watch-event fast path.
//
//   kfw_*  — locate the envelope fields of one watch line
//            ({"type": ..., "object": {... "metadata": {...} ...}})
//            WITHOUT building a document tree.  The Python side slices
//            the returned byte ranges out of the original line, decodes
//            only the (small) metadata object eagerly, and defers the
//            full body until the informer actually admits the object.
//
// This is deliberately a *scanner*, not a validator: it tracks strings,
// escapes and brace/bracket depth precisely, but does not check number
// grammar or literal spelling.  The Python wrapper json.loads()es every
// slice it extracts, so a line the scanner mis-ranges fails there and
// falls back to a full-document json.loads — wrong output is impossible,
// only a slow path.
//
// ABI (mirrors packer.cc's out-array style): offsets written into a
// caller-provided int64 array, 0 on success, -1 on error with the
// message available from kfw_last_error().

#include <cstdint>
#include <cstring>
#include <string>

namespace {

// The five characters the container skip-loop must stop on; everything
// else is consumed at one table load per byte (the scan is the whole
// per-event native cost, so the inner loop matters).
const bool* structural_table() {
  static bool t[256] = {};
  static const bool init = [] {
    t[static_cast<unsigned char>('"')] = true;
    t[static_cast<unsigned char>('{')] = true;
    t[static_cast<unsigned char>('[')] = true;
    t[static_cast<unsigned char>('}')] = true;
    t[static_cast<unsigned char>(']')] = true;
    return true;
  }();
  (void)init;
  return t;
}

struct Scan {
  const char* p;
  const char* end;
  const char* err = nullptr;

  explicit Scan(const char* buf, int64_t len) : p(buf), end(buf + len) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool fail(const char* msg) {
    err = msg;
    return false;
  }

  // Find the next '"' or '\\' at or after p.  SWAR over 8-byte words:
  // k8s documents are mostly short strings, where memchr's per-call
  // setup costs more than it saves — one word load + two XOR masks per
  // 8 bytes beats both memchr and a byte loop.
  static const char* quote_or_escape(const char* p, const char* end) {
    constexpr uint64_t kOnes = 0x0101010101010101ULL;
    constexpr uint64_t kHigh = 0x8080808080808080ULL;
    constexpr uint64_t kQuote = 0x2222222222222222ULL;   // '"'
    constexpr uint64_t kSlash = 0x5C5C5C5C5C5C5C5CULL;   // '\\'
    while (p + 8 <= end) {
      uint64_t w;
      std::memcpy(&w, p, 8);
      uint64_t q = w ^ kQuote;
      uint64_t b = w ^ kSlash;
      uint64_t hit = ((q - kOnes) & ~q & kHigh) | ((b - kOnes) & ~b & kHigh);
      if (hit != 0) return p + (__builtin_ctzll(hit) >> 3);
      p += 8;
    }
    while (p < end && *p != '"' && *p != '\\') ++p;
    return p;
  }

  // Advance past one JSON string (p on the opening quote); the content
  // range (between the quotes) is returned via [cs, ce).
  bool str(const char** cs, const char** ce) {
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    *cs = p;
    const char* q = p;
    while (true) {
      q = quote_or_escape(q, end);
      if (q >= end) {
        p = end;
        return fail("unterminated string");
      }
      if (*q == '"') {
        *ce = q;
        p = q + 1;
        return true;
      }
      q += 2;  // escape pair
    }
  }

  // Advance past one JSON value of any kind.  Depth-counts containers,
  // skips strings with escape handling, and consumes number/literal
  // runs up to the next structural delimiter.
  bool value() {
    ws();
    if (p >= end) return fail("unexpected end of input");
    char c = *p;
    if (c == '"') {
      const char *s, *e;
      return str(&s, &e);
    }
    if (c == '{' || c == '[') {
      const bool* stop = structural_table();
      int depth = 0;
      while (p < end) {
        c = *p;
        if (!stop[static_cast<unsigned char>(c)]) {
          ++p;
          continue;
        }
        if (c == '"') {
          const char *s, *e;
          if (!str(&s, &e)) return false;
          continue;
        }
        if (c == '{' || c == '[') {
          ++depth;
        } else {
          --depth;
          if (depth == 0) {
            ++p;
            return true;
          }
        }
        ++p;
      }
      return fail("unterminated container");
    }
    // number / true / false / null — consume until a delimiter.
    while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
           *p != '\t' && *p != '\n' && *p != '\r')
      ++p;
    return true;
  }

  // Iterate the members of the object starting at p (on '{').  The
  // callback sees each key's content range positioned AT the value and
  // must consume it (typically via value(), or by recursing into
  // object_members for keys it wants to look inside — this is what
  // keeps the whole event a single pass).
  template <typename F>
  bool object_members(F&& consume_value) {
    ws();
    if (p >= end || *p != '{') return fail("expected object");
    ++p;
    ws();
    if (p < end && *p == '}') {
      ++p;
      return true;
    }
    while (true) {
      ws();
      const char *ks, *ke;
      if (!str(&ks, &ke)) return false;
      ws();
      if (p >= end || *p != ':') return fail("expected ':'");
      ++p;
      ws();
      if (!consume_value(ks, ke)) return false;
      ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == '}') {
        ++p;
        return true;
      }
      return fail("expected ',' or '}'");
    }
  }
};

bool key_is(const char* ks, const char* ke, const char* want) {
  size_t n = std::strlen(want);
  return static_cast<size_t>(ke - ks) == n && std::memcmp(ks, want, n) == 0;
}

thread_local std::string g_error;

}  // namespace

extern "C" {

const char* kfw_last_error() { return g_error.c_str(); }

// out[0..1]:  "type" value content (string, without quotes)
// out[2..3]:  "object" value (whole JSON value)
// out[4..5]:  "metadata" value inside object, or -1/-1 when absent
// out[6..7]:  metadata.name string content, or -1/-1 when not extracted
// out[8..9]:  metadata.namespace string content, ditto
// out[10..11]: metadata.resourceVersion string content, ditto
//
// The three field ranges are an *optimization*, not an answer: a field
// is only extracted when its value is an escape-free string, so -1/-1
// means "parse the metadata slice to find out", never "absent".  All
// offsets are byte positions into buf.  Returns 0 on success, -1 on
// any structural problem (caller falls back to a full json.loads).
int kfw_scan_event(const char* buf, int64_t len, int64_t* out) {
  if (buf == nullptr || out == nullptr || len < 0) {
    g_error = "bad arguments";
    return -1;
  }
  for (int i = 0; i < 12; ++i) out[i] = -1;
  Scan s(buf, len);
  const char *tvs = nullptr, *tve = nullptr;  // type content
  const char *ovs = nullptr, *ove = nullptr;  // object value
  // Single pass: the envelope iteration recurses member-aware into
  // "object" and from there into "metadata", so no byte is scanned
  // twice — the scan IS the per-event native cost.
  //
  // String-valued identity fields are extracted only when escape-free;
  // anything else stays -1/-1 and the Python side parses the metadata
  // slice on first touch (-1 means "go find out", never "absent").
  auto put_string = [&](int slot, const char* vs, const char* ve) {
    if (ve - vs < 2 || *vs != '"') return;
    const char* cs = vs + 1;
    const char* ce = ve - 1;
    if (std::memchr(cs, '\\', ce - cs) != nullptr) return;
    out[slot] = cs - buf;
    out[slot + 1] = ce - buf;
  };
  bool ok = s.object_members([&](const char* ks, const char* ke) -> bool {
    if (key_is(ks, ke, "type") && s.p < s.end && *s.p == '"') {
      const char *cs, *ce;
      if (!s.str(&cs, &ce)) return false;
      tvs = cs;
      tve = ce;
      return true;
    }
    if (key_is(ks, ke, "object")) {
      // Duplicate keys: json.loads keeps the LAST occurrence, so any
      // ranges recorded for an earlier "object"/"metadata" must be
      // dropped before scanning this one.
      for (int i = 4; i < 12; ++i) out[i] = -1;
      ovs = s.p;
      if (s.p < s.end && *s.p == '{') {
        bool iok = s.object_members([&](const char* ks2,
                                        const char* ke2) -> bool {
          if (key_is(ks2, ke2, "metadata")) {
            for (int i = 4; i < 12; ++i) out[i] = -1;
          }
          // Only an object-typed metadata is fast-pathable; a scalar
          // here (never produced by a real apiserver) stays
          // un-extracted so the Python side materializes the body and
          // sees the same value a full json.loads would.
          if (key_is(ks2, ke2, "metadata") && s.p < s.end && *s.p == '{') {
            const char* mvs = s.p;
            bool mok = s.object_members([&](const char* ks3,
                                            const char* ke3) -> bool {
              const char* vvs = s.p;
              if (!s.value()) return false;
              if (key_is(ks3, ke3, "name")) {
                out[6] = out[7] = -1;  // dup key: last wins
                put_string(6, vvs, s.p);
              } else if (key_is(ks3, ke3, "namespace")) {
                out[8] = out[9] = -1;
                put_string(8, vvs, s.p);
              } else if (key_is(ks3, ke3, "resourceVersion")) {
                out[10] = out[11] = -1;
                put_string(10, vvs, s.p);
              }
              return true;
            });
            if (!mok) return false;
            out[4] = mvs - buf;
            out[5] = s.p - buf;
            return true;
          }
          return s.value();
        });
        if (!iok) return false;
      } else if (!s.value()) {
        return false;  // ERROR events may carry a Status or scalar
      }
      ove = s.p;
      return true;
    }
    return s.value();
  });
  if (!ok) {
    g_error = s.err ? s.err : "scan failed";
    return -1;
  }
  s.ws();
  if (s.p != s.end) {
    g_error = "trailing data after envelope";
    return -1;
  }
  if (tvs == nullptr) {
    g_error = "missing or non-string 'type'";
    return -1;
  }
  if (ovs == nullptr) {
    g_error = "missing 'object'";
    return -1;
  }
  out[0] = tvs - buf;
  out[1] = tve - buf;
  out[2] = ovs - buf;
  out[3] = ove - buf;
  return 0;
}

}  // extern "C"
