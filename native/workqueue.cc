// kfq_*: native delaying, rate-limited, deduplicating workqueue.
//
// Mirrors kubeflow_tpu/platform/runtime/controller.py::_WorkQueue (which in
// turn mirrors the Go client-go util/workqueue the reference controllers use).
// Keys are opaque int64s — the Python side maps Request objects to ids so the
// hot enqueue/dequeue path (every watch event for every controller) runs
// without the GIL-held Python heap operations.
//
// Semantics (must stay in lock-step with the Python implementation):
//   * add(key, delay): an entry at least as early already pending → no-op;
//     otherwise (re)schedule, superseding any later pending entry.  A key
//     currently processing (returned by get, not yet done) parks in the
//     dirty set instead and re-enqueues on done — per-key mutual exclusion
//     so multiple workers never reconcile one key concurrently (client-go
//     workqueue semantics).
//   * add_rate_limited(key): exponential backoff 2^failures * base, capped.
//   * forget(key): reset the failure count (called after a clean reconcile).
//   * get(timeout): block until an entry is due or timeout; pops the live
//     entry, dropping stale superseded heap nodes; marks it processing.
//   * done(key): release the key; a parked dirty re-add fires (earliest
//     requested time wins, so backoffs aren't flattened to immediate).

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <queue>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace kfq {

using Clock = std::chrono::steady_clock;
using TimePoint = Clock::time_point;

struct Entry {
  TimePoint when;
  uint64_t seq;
  int64_t key;
  bool operator>(const Entry& o) const {
    if (when != o.when) return when > o.when;
    return seq > o.seq;
  }
};

class Queue {
 public:
  Queue(double base_delay_s, double max_delay_s)
      : base_(base_delay_s), max_(max_delay_s) {}

  void add(int64_t key, double delay_s) {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    TimePoint when =
        Clock::now() + std::chrono::duration_cast<Clock::duration>(
                           std::chrono::duration<double>(delay_s < 0 ? 0 : delay_s));
    if (processing_.count(key)) {
      auto d = dirty_.find(key);
      if (d == dirty_.end() || when < d->second) dirty_[key] = when;
      return;
    }
    auto it = pending_.find(key);
    if (it != pending_.end() && it->second.second <= when) return;
    ++seq_;
    pending_[key] = {seq_, when};
    heap_.push(Entry{when, seq_, key});
    cv_.notify_one();
  }

  void done(int64_t key) {
    std::lock_guard<std::mutex> lk(mu_);
    processing_.erase(key);
    auto d = dirty_.find(key);
    if (d == dirty_.end()) return;
    TimePoint when = d->second;
    dirty_.erase(d);
    if (shutdown_) return;
    ++seq_;
    pending_[key] = {seq_, when};
    heap_.push(Entry{when, seq_, key});
    cv_.notify_one();
  }

  bool is_processing(int64_t key) {
    std::lock_guard<std::mutex> lk(mu_);
    return processing_.count(key) != 0;
  }

  void add_rate_limited(int64_t key) {
    double delay;
    {
      std::lock_guard<std::mutex> lk(mu_);
      int n = failures_[key]++;
      delay = base_ * static_cast<double>(1ULL << (n > 62 ? 62 : n));
      if (delay > max_) delay = max_;
    }
    add(key, delay);
  }

  void forget(int64_t key) {
    std::lock_guard<std::mutex> lk(mu_);
    failures_.erase(key);
  }

  int failures(int64_t key) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = failures_.find(key);
    return it == failures_.end() ? 0 : it->second;
  }

  bool is_pending(int64_t key) {
    std::lock_guard<std::mutex> lk(mu_);
    return pending_.count(key) != 0 || dirty_.count(key) != 0;
  }

  // Returns the popped key, or -1 on timeout / shutdown.
  int64_t get(double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    TimePoint deadline =
        Clock::now() + std::chrono::duration_cast<Clock::duration>(
                           std::chrono::duration<double>(timeout_s));
    while (true) {
      if (shutdown_) return -1;
      TimePoint now = Clock::now();
      // Drop stale heap nodes eagerly.
      while (!heap_.empty()) {
        const Entry& top = heap_.top();
        auto it = pending_.find(top.key);
        if (it == pending_.end() || it->second.first != top.seq) {
          heap_.pop();
          continue;
        }
        break;
      }
      if (!heap_.empty() && heap_.top().when <= now) {
        Entry e = heap_.top();
        heap_.pop();
        pending_.erase(e.key);
        processing_.insert(e.key);
        return e.key;
      }
      if (now >= deadline) return -1;
      TimePoint until = deadline;
      if (!heap_.empty() && heap_.top().when < until) until = heap_.top().when;
      cv_.wait_until(lk, until);
    }
  }

  size_t pending_count() {
    std::lock_guard<std::mutex> lk(mu_);
    return pending_.size() + dirty_.size();
  }

  void shut_down() {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_ = true;
    cv_.notify_all();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> heap_;
  // key -> (seq of live entry, scheduled time)
  std::unordered_map<int64_t, std::pair<uint64_t, TimePoint>> pending_;
  std::unordered_set<int64_t> processing_;
  std::unordered_map<int64_t, TimePoint> dirty_;
  std::unordered_map<int64_t, int> failures_;
  uint64_t seq_ = 0;
  double base_;
  double max_;
  bool shutdown_ = false;
};

}  // namespace kfq

extern "C" {

void* kfq_new(double base_delay_s, double max_delay_s) {
  return new kfq::Queue(base_delay_s, max_delay_s);
}

void kfq_delete(void* q) { delete static_cast<kfq::Queue*>(q); }

void kfq_add(void* q, int64_t key, double delay_s) {
  static_cast<kfq::Queue*>(q)->add(key, delay_s);
}

void kfq_add_rate_limited(void* q, int64_t key) {
  static_cast<kfq::Queue*>(q)->add_rate_limited(key);
}

void kfq_forget(void* q, int64_t key) {
  static_cast<kfq::Queue*>(q)->forget(key);
}

int kfq_failures(void* q, int64_t key) {
  return static_cast<kfq::Queue*>(q)->failures(key);
}

int kfq_is_pending(void* q, int64_t key) {
  return static_cast<kfq::Queue*>(q)->is_pending(key) ? 1 : 0;
}

int64_t kfq_get(void* q, double timeout_s) {
  return static_cast<kfq::Queue*>(q)->get(timeout_s);
}

void kfq_done(void* q, int64_t key) {
  static_cast<kfq::Queue*>(q)->done(key);
}

int kfq_is_processing(void* q, int64_t key) {
  return static_cast<kfq::Queue*>(q)->is_processing(key) ? 1 : 0;
}

int64_t kfq_pending(void* q) {
  return static_cast<int64_t>(static_cast<kfq::Queue*>(q)->pending_count());
}

void kfq_shutdown(void* q) { static_cast<kfq::Queue*>(q)->shut_down(); }

}  // extern "C"
